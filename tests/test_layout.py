"""Slot-layout suite (PR 11): packed 32 B rows behind one descriptor.

Covers the acceptance surface of the layout tentpole:

* ``full`` is byte-identical to the pre-layout table (pinned);
* pack/unpack round-trips are exact in the packed domain and preserve
  every decision-relevant field through the canonical full row;
* packed tables are decision-for-decision equal to the full-layout
  oracle, locally and on the 8-device mesh, through time steps,
  duplicate keys and behavior flags;
* cross-layout state movement is conservative: checkpoint frames written
  under ``packed`` restore under ``full`` (and vice versa), handoff
  chunks cross layouts through the real TransferState pb, and telemetry
  scans agree with the host oracle per layout;
* off-family traffic migrates a packed table to full instead of erroring
  or corrupting bytes.
"""

import numpy as np
import pytest

from gubernator_tpu.ops import layout as layout_mod
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.layout import FULL, GCRA32, TOKEN32, resolve_layout
from gubernator_tpu.ops.table2 import (
    EXP_HI, EXP_LO, F, FLAGS, K, LIMIT, REM_I, decode_live_slots,
)

NOW = 1_700_000_000_000


def cols(fp, algo, hits=1, limit=64, dur=8_000, behavior=0, now=NOW):
    n = fp.shape[0]
    h = (
        np.asarray(hits, dtype=np.int64)
        if np.ndim(hits) else np.full(n, hits, dtype=np.int64)
    )
    b = (
        np.asarray(behavior, dtype=np.int32)
        if np.ndim(behavior) else np.full(n, behavior, dtype=np.int32)
    )
    return RequestColumns(
        fp=fp.astype(np.int64),
        algo=np.full(n, algo, dtype=np.int32),
        behavior=b,
        hits=h,
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, dur, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def rc_equal(a, b, fields=("status", "limit", "remaining", "reset_time", "err")):
    for f in fields:
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f
        )


# ------------------------------------------------------------ descriptors


def test_layout_registry_and_resolution(monkeypatch):
    assert resolve_layout("full") is FULL
    assert resolve_layout("gcra32") is GCRA32
    assert resolve_layout("token32") is TOKEN32
    assert resolve_layout("auto") is FULL  # no hint → today's bytes
    assert resolve_layout("packed", math_hint="gcra") is GCRA32
    assert resolve_layout("packed", math_hint="token") is TOKEN32
    assert resolve_layout("packed", math_hint="mixed") is FULL
    monkeypatch.setenv("GUBER_SLOT_LAYOUT", "gcra32")
    assert resolve_layout() is GCRA32
    monkeypatch.setenv("GUBER_SLOT_LAYOUT", "bogus")
    with pytest.raises(ValueError):
        resolve_layout()
    assert layout_mod.layout_by_code(0) is FULL
    assert layout_mod.layout_by_code(1) is GCRA32
    assert layout_mod.layout_by_code(2) is TOKEN32
    with pytest.raises(ValueError):
        layout_mod.layout_by_code(9)


def test_packed_layouts_halve_slot_bytes():
    assert FULL.slot_bytes == 64 and FULL.row == 128
    for lay in (GCRA32, TOKEN32):
        assert lay.slot_bytes == 32 and lay.row == 64
        assert lay.slot_bytes <= 0.55 * FULL.slot_bytes


def _gcra_full_row(rng, n):
    """Random plausible full-width GCRA slot rows."""
    full = np.zeros((n, F), dtype=np.int32)
    fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
    tat = NOW + rng.integers(0, 1 << 40, size=n, dtype=np.int64)
    dur = rng.integers(1, 1 << 40, size=n, dtype=np.int64)
    full[:, 0] = fp & 0xFFFFFFFF
    full[:, 1] = fp >> 32
    full[:, LIMIT] = rng.integers(1, 1 << 30, size=n)
    full[:, 3] = rng.integers(1, 1 << 30, size=n)  # burst
    full[:, FLAGS] = 2 | (rng.integers(0, 2, size=n).astype(np.int32) << 8)
    full[:, 6] = dur & 0xFFFFFFFF
    full[:, 7] = dur >> 32
    full[:, EXP_LO] = tat & 0xFFFFFFFF
    full[:, EXP_HI] = tat >> 32
    full[:, 12] = tat >> 32  # REMF_HI = hi32(aux)
    full[:, 13] = tat & 0xFFFFFFFF  # REMF_LO = lo32(aux)
    return full


def test_gcra32_roundtrip_exact():
    rng = np.random.default_rng(1)
    full = _gcra_full_row(rng, 256)
    packed = np.asarray(GCRA32.pack(full))
    assert packed.shape == (256, 8)
    # packed-domain round trip is the identity
    np.testing.assert_array_equal(
        np.asarray(GCRA32.pack(np.asarray(GCRA32.unpack(packed)))), packed
    )
    back = np.asarray(GCRA32.unpack(packed))
    # every decision-relevant field survives (stamp is dropped by design)
    for i in (0, 1, LIMIT, 3, REM_I, FLAGS, 6, 7, EXP_LO, EXP_HI, 12, 13):
        np.testing.assert_array_equal(back[:, i], full[:, i], err_msg=str(i))


def test_token32_roundtrip_exact():
    rng = np.random.default_rng(2)
    n = 256
    full = np.zeros((n, F), dtype=np.int32)
    fp = rng.integers(1, (1 << 63) - 1, size=n, dtype=np.int64)
    dur = rng.integers(1, 1 << 40, size=n, dtype=np.int64)
    stamp = NOW - rng.integers(0, 1 << 30, size=n, dtype=np.int64)
    exp = stamp + dur  # the token invariant the layout relies on
    full[:, 0] = fp & 0xFFFFFFFF
    full[:, 1] = fp >> 32
    full[:, LIMIT] = rng.integers(1, 1 << 30, size=n)
    full[:, REM_I] = rng.integers(0, 1 << 30, size=n)
    full[:, FLAGS] = 0 | (rng.integers(0, 2, size=n).astype(np.int32) << 8)
    full[:, 6] = dur & 0xFFFFFFFF
    full[:, 7] = dur >> 32
    full[:, 8] = stamp & 0xFFFFFFFF
    full[:, 9] = stamp >> 32
    full[:, EXP_LO] = exp & 0xFFFFFFFF
    full[:, EXP_HI] = exp >> 32
    packed = np.asarray(TOKEN32.pack(full))
    np.testing.assert_array_equal(
        np.asarray(TOKEN32.pack(np.asarray(TOKEN32.unpack(packed)))), packed
    )
    back = np.asarray(TOKEN32.unpack(packed))
    # stamp derives exactly from exp - duration under the invariant
    for i in (0, 1, LIMIT, REM_I, FLAGS, 6, 7, 8, 9, EXP_LO, EXP_HI):
        np.testing.assert_array_equal(back[:, i], full[:, i], err_msg=str(i))


def test_zero_rows_stay_empty_through_roundtrip():
    z = np.zeros((4, 8), dtype=np.int32)
    for lay in (GCRA32, TOKEN32):
        back = np.asarray(lay.unpack(z))
        assert (back[:, 0] == 0).all() and (back[:, 1] == 0).all()
        np.testing.assert_array_equal(np.asarray(lay.pack(back)), z)


# ------------------------------------------------------- byte-identity pin


def test_full_layout_byte_identical_to_default():
    """GUBER_SLOT_LAYOUT=full is today's table, bit for bit."""
    rng = np.random.default_rng(3)
    fp = rng.integers(1, (1 << 63) - 1, size=512, dtype=np.int64)
    a = LocalEngine(capacity=1 << 12, write_mode="xla", layout="full")
    b = LocalEngine(capacity=1 << 12, write_mode="xla")  # pre-layout default
    for t in (NOW, NOW + 900, NOW + 9_000):
        ca = cols(fp, 0, hits=2, now=t)
        rc_equal(
            a.check_columns(ca, now_ms=t), b.check_columns(ca, now_ms=t)
        )
    np.testing.assert_array_equal(
        np.asarray(a.table.rows), np.asarray(b.table.rows)
    )
    assert a.table.rows.shape[-1] == 128


# ------------------------------------------------------------ decision parity


@pytest.mark.parametrize("lay,algo", [("gcra32", 2), ("token32", 0)])
def test_packed_decision_parity_local(lay, algo):
    rng = np.random.default_rng(11)
    fp = rng.integers(1, (1 << 63) - 1, size=512, dtype=np.int64)
    full_e = LocalEngine(capacity=1 << 13, write_mode="xla", layout="full")
    pack_e = LocalEngine(capacity=1 << 13, write_mode="xla", layout=lay)
    assert pack_e.table.rows.shape[-1] == 64
    t = NOW
    for step in range(8):
        t += int(rng.integers(50, 3_000))
        sel = fp.copy()
        if step == 3:
            sel[256:] = sel[:256]  # duplicate keys → pass planner
        hits = rng.integers(0, 5, size=512)
        beh = rng.choice([0, 8, 32], size=512).astype(np.int32)
        c = cols(sel, algo, hits=hits, limit=16, behavior=beh, now=t)
        rc_equal(
            full_e.check_columns(c, now_ms=t),
            pack_e.check_columns(c, now_ms=t),
        )
    assert pack_e.stats.layout_migrations == 0
    assert full_e.live_count(t) == pack_e.live_count(t)


@pytest.mark.parametrize("lay,algo", [("gcra32", 2), ("token32", 0)])
def test_packed_decision_parity_mesh(lay, algo):
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    mesh = make_mesh(8)
    kw = dict(capacity_per_shard=1 << 10, write_mode="xla",
              route="device", dedup="device")
    full_e = ShardedEngine(mesh, layout="full", **kw)
    pack_e = ShardedEngine(mesh, layout=lay, **kw)
    rng = np.random.default_rng(12)
    fp = rng.integers(1, (1 << 63) - 1, size=1024, dtype=np.int64)
    t = NOW
    for step in range(4):
        t += int(rng.integers(100, 2_000))
        sel = fp.copy()
        if step == 2:
            sel[512:] = sel[:512]
        c = cols(sel, algo, hits=rng.integers(0, 4, size=1024), limit=32,
                 now=t)
        rc_equal(
            full_e.check_columns(c, now_ms=t),
            pack_e.check_columns(c, now_ms=t),
        )
    assert pack_e.stats.layout_migrations == 0


def test_offfamily_traffic_migrates_packed_table():
    rng = np.random.default_rng(13)
    fp = rng.integers(1, (1 << 63) - 1, size=128, dtype=np.int64)
    e = LocalEngine(capacity=1 << 12, write_mode="xla", layout="gcra32")
    e.check_columns(cols(fp, 2, hits=3, limit=16), now_ms=NOW)
    # token traffic arrives → migrate, don't corrupt: the gcra rows survive
    rc = e.check_columns(cols(fp[:8], 0, hits=1), now_ms=NOW)
    assert (rc.err == 0).all()
    assert e.table.layout is FULL
    assert e.stats.layout_migrations == 1
    # the untouched gcra keys still answer from their migrated state
    probe = e.check_columns(
        cols(fp[8:], 2, hits=0, limit=16), now_ms=NOW
    )
    fresh = LocalEngine(capacity=1 << 12, write_mode="xla")
    fresh.check_columns(cols(fp, 2, hits=3, limit=16), now_ms=NOW)
    want = fresh.check_columns(
        cols(fp[8:], 2, hits=0, limit=16), now_ms=NOW
    )
    rc_equal(probe, want)


# ------------------------------------------------------ checkpoint round-trips


def _live_full_map(engine, now):
    """Live keys → canonical full-row bytes with the stamp lanes zeroed:
    packed layouts drop the stamp by design (gcra32) or derive it
    (token32), so cross-layout equality is over the decision-relevant
    fields."""
    lay = engine.table.layout
    rows = np.asarray(engine.table.rows)
    slots, fps, _ = decode_live_slots(rows, now, layout=lay)
    full = np.asarray(lay.unpack(slots)).copy()
    full[:, 8] = 0  # STAMP_LO
    full[:, 9] = 0  # STAMP_HI
    return {int(f): s.tobytes() for f, s in zip(fps, full)}


@pytest.mark.parametrize("src_lay,dst_lay", [
    ("gcra32", "full"), ("full", "gcra32"),
    ("token32", "full"), ("full", "token32"),
])
def test_checkpoint_cross_layout_restore(tmp_path, src_lay, dst_lay):
    """Frames written under one layout replay into an engine booted with
    another — through the canonical full row, conservatively."""
    from gubernator_tpu.ops.checkpoint import (
        EpochTracker, extract_begin, finish_extract,
    )
    from gubernator_tpu.store import DeltaLog, fps_from_slots

    algo = 2 if "gcra" in (src_lay + dst_lay) else 0
    rng = np.random.default_rng(21)
    fp = rng.integers(1, (1 << 63) - 1, size=600, dtype=np.int64)
    src = LocalEngine(capacity=1 << 12, write_mode="xla", layout=src_lay)
    src.ckpt = EpochTracker(src.table.rows.shape[0])
    src.check_columns(cols(fp, algo, hits=3, limit=16), now_ms=NOW)
    _, gids = src.ckpt.take()
    fps, slots = finish_extract(extract_begin(
        src.table.rows, gids, src.ckpt.blk, NOW, layout=src.table.layout
    ))
    assert slots.shape[1] == src.table.layout.F
    log = DeltaLog(str(tmp_path / "x.delta"))
    nbytes = log.append(1, NOW, slots, layout=src.table.layout)
    if src.table.layout is not FULL:
        # packed frames carry ~half the bytes of the full-layout frame
        assert nbytes < 0.6 * (slots.shape[0] * 64 + 64)
    scan = log.scan()
    assert scan.error is None and len(scan.frames) == 1
    _e, _t, f_slots, f_layout = scan.frames[0]
    assert f_layout is src.table.layout
    dst = LocalEngine(capacity=1 << 12, write_mode="xla", layout=dst_lay)
    merged = dst.merge_rows(
        fps_from_slots(f_slots), f_slots, now_ms=NOW, layout=f_layout
    )
    assert merged == fps.shape[0]
    # replay reconstructed the live state exactly (same-algo rows, no
    # conservative tightening was needed — equality is the strong check)
    assert _live_full_map(dst, NOW) == _live_full_map(src, NOW)
    # idempotent replay stays conservative: a second merge changes nothing
    dst.merge_rows(
        fps_from_slots(f_slots), f_slots, now_ms=NOW, layout=f_layout
    )
    assert _live_full_map(dst, NOW) == _live_full_map(src, NOW)


def test_snapshot_cross_layout_restore():
    rng = np.random.default_rng(22)
    fp = rng.integers(1, (1 << 63) - 1, size=400, dtype=np.int64)
    src = LocalEngine(capacity=1 << 12, write_mode="xla", layout="gcra32")
    src.check_columns(cols(fp, 2, hits=2, limit=16), now_ms=NOW)
    snap = src.snapshot()
    dst = LocalEngine(capacity=1 << 12, write_mode="xla", layout="full")
    dst.restore(snap, layout=src.table.layout)
    assert _live_full_map(dst, NOW) == _live_full_map(src, NOW)
    # and back: full snapshot into a packed engine of the same family
    back = LocalEngine(capacity=1 << 12, write_mode="xla", layout="gcra32")
    back.restore(dst.snapshot(), layout=FULL)
    assert back.table.layout is GCRA32
    assert _live_full_map(back, NOW) == _live_full_map(src, NOW)


def test_snapshot_offfamily_restore_degrades_to_full():
    rng = np.random.default_rng(23)
    fp = rng.integers(1, (1 << 63) - 1, size=64, dtype=np.int64)
    src = LocalEngine(capacity=1 << 12, write_mode="xla", layout="full")
    src.check_columns(cols(fp, 0, hits=1), now_ms=NOW)  # token rows
    dst = LocalEngine(capacity=1 << 12, write_mode="xla", layout="gcra32")
    dst.restore(src.snapshot(), layout=FULL)
    assert dst.table.layout is FULL  # engine degraded rather than corrupt
    assert _live_full_map(dst, NOW) == _live_full_map(src, NOW)


# ------------------------------------------------------------ handoff wire


def test_handoff_chunks_cross_layouts_via_pb():
    """Extract on a packed sender → real TransferState pb → merge into a
    full-layout receiver (and the reverse), row-for-row."""
    from gubernator_tpu.proto import handoff_pb2 as handoff_pb
    from gubernator_tpu.service.wire import (
        transfer_chunk_arrays, transfer_chunk_pb,
    )

    rng = np.random.default_rng(31)
    fp = rng.integers(1, (1 << 63) - 1, size=300, dtype=np.int64)
    for send_lay, recv_lay in (("gcra32", "full"), ("full", "gcra32")):
        src = LocalEngine(capacity=1 << 12, write_mode="xla", layout=send_lay)
        src.check_columns(cols(fp, 2, hits=2, limit=16), now_ms=NOW)
        fps, slots = src.extract_live(NOW)
        assert slots.shape[1] == src.table.layout.F
        pts = np.arange(fps.shape[0], dtype=np.uint32)
        req = transfer_chunk_pb(
            "t-lay", 0, 1, "src:1", NOW, fps, pts, slots,
            layout=src.table.layout,
        )
        # through real proto bytes — the mixed-version wire surface
        req2 = handoff_pb.TransferStateReq.FromString(req.SerializeToString())
        r_fps, _r_pts, r_slots, r_layout = transfer_chunk_arrays(req2)
        assert r_layout is src.table.layout
        dst = LocalEngine(capacity=1 << 12, write_mode="xla", layout=recv_lay)
        merged = dst.merge_rows(r_fps, r_slots, now_ms=NOW, layout=r_layout)
        assert merged == fps.shape[0]
        assert _live_full_map(dst, NOW) == _live_full_map(src, NOW)


def test_legacy_chunk_without_layout_field_decodes_as_full():
    from gubernator_tpu.service.wire import (
        transfer_chunk_arrays, transfer_chunk_pb,
    )

    rng = np.random.default_rng(32)
    fp = rng.integers(1, (1 << 63) - 1, size=32, dtype=np.int64)
    src = LocalEngine(capacity=1 << 10, write_mode="xla", layout="full")
    src.check_columns(cols(fp, 0, hits=1), now_ms=NOW)
    fps, slots = src.extract_live(NOW)
    req = transfer_chunk_pb(
        "t-old", 0, 1, "src:1", NOW,
        fps, np.arange(fps.shape[0], dtype=np.uint32), slots,
    )
    assert req.layout == 0  # proto3 default — pre-layout senders look the same
    _f, _p, s, lay = transfer_chunk_arrays(req)
    assert lay is FULL and s.shape[1] == 16


# ------------------------------------------------------------- telemetry


@pytest.mark.parametrize("lay,algo", [
    ("full", 2), ("gcra32", 2), ("token32", 0),
])
def test_telemetry_parity_per_layout(lay, algo):
    from gubernator_tpu.ops.telemetry import finish_scan, host_telemetry

    rng = np.random.default_rng(41)
    fp = rng.integers(1, (1 << 63) - 1, size=2_000, dtype=np.int64)
    e = LocalEngine(capacity=1 << 13, write_mode="xla", layout=lay)
    e.check_columns(cols(fp, algo, hits=3, limit=4), now_ms=NOW)
    snap = finish_scan(e.telemetry_begin(NOW + 1))
    oracle = host_telemetry(
        np.asarray(e.table.rows), NOW + 1, layout=e.table.layout
    )
    for f in ("live_keys", "occupied_slots", "over_keys",
              "bucket_occupancy", "ttl_horizon", "remaining_frac",
              "block_fill"):
        assert getattr(snap, f) == getattr(oracle, f), f
    # a handful of inserts can drop to per-bucket overflow at this load;
    # parity above is the contract, near-totality the sanity floor
    assert snap.live_keys >= 0.99 * fp.shape[0]
