"""HBM-resident rate-limit state: a bucketized hash table tuned for TPU.

This replaces the reference's per-worker LRU caches (reference lrucache.go:32-178,
workers.go:19-37): instead of N goroutine-private `map[string]*list.Element`
shards, a fixed-capacity slot table lives in device HBM and is mutated in place
by the vectorized decision kernel (ops/kernel.py) with donated buffers.

Layout is dictated by measured TPU memory-op costs (see kernel.py): 32-bit flat
scatters and narrow row gathers vectorize; anything 64-bit or row-scattered
serializes under the X64-emulation pass. Hence:

* capacity C is divided into NB = C/K **buckets** of K slots; a key hashes to
  one bucket and may occupy any lane in it (the probe window of the reference's
  worker-cache probing becomes one contiguous bucket row).
* the **probe plane** is three (NB, K) float32-carrier arrays — fp_lo, fp_hi
  (the 63-bit fingerprint split in halves) and exp_c (expiry in ~1s coarse
  units) — so one probe is three vectorized row gathers.
* the **apply plane** is twelve flat (C,) float32-carrier arrays holding the
  full per-slot state; int32 values travel bitcast inside float32 (TPU's fast
  path), int64 millisecond timestamps are split lo/hi, and the leaky-bucket
  float64 remainder (reference store.go:32) is stored double-single as
  (remf_hi, remf_lo) float32 with ~48-bit effective mantissa.

Field semantics mirror TokenBucketItem/LeakyBucketItem (reference store.go:29-43)
plus CacheItem.ExpireAt (reference cache.go:29-41). ``stamp`` holds
TokenBucketItem.CreatedAt for token slots and LeakyBucketItem.UpdatedAt for
leaky slots. fp == 0 marks an empty slot (fingerprints are remapped away from
0, hashing.py). CacheItem.InvalidAt (persistent-store revalidation) is handled
by the host Store layer, not the device table.

Eviction is expiry-stamp based rather than LRU: a slot whose expiry has passed
is dead (the reference removes expired items on read, lrucache.go:111-128) and
may be reclaimed by any key probing its bucket; when a bucket is full of live
slots the soonest-expiring lane is evicted and counted as an "unexpired
eviction" (reference alarm metric, lrucache.go:138-149).

Documented range limits vs the reference's int64 fields: `limit` and `burst`
must fit int32 (|v| < 2^31); the front door rejects larger values with a
per-request error. Stored token `remaining` saturates at int32 range.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# apply-plane array names, in Table field order (all (C,) float32 carriers)
APPLY_FIELDS = (
    "limit",  # int32 bitcast
    "burst",  # int32 bitcast
    "rem_i",  # int32 bitcast (token remaining, saturating)
    "flags",  # int32 bitcast: algo | status << 8
    "dur_lo",  # int64 duration split lo/hi (raw request duration, ms)
    "dur_hi",
    "stamp_lo",  # int64 CreatedAt/UpdatedAt epoch ms split
    "stamp_hi",
    "exp_lo",  # int64 ExpireAt epoch ms split (exact; reset_time source)
    "exp_hi",
    "remf_hi",  # float64 leaky remainder, double-single hi part (true f32)
    "remf_lo",  # double-single lo part (true f32)
)

# coarse expiry shift: probe-plane expiry is (ms >> EXPC_SHIFT) ≈ 1.024 s units
EXPC_SHIFT = 10


class Table(NamedTuple):
    # probe plane (NB, K) f32 carriers
    pfp_lo: jnp.ndarray
    pfp_hi: jnp.ndarray
    pexp_c: jnp.ndarray
    # apply plane (C,) f32 carriers, order = APPLY_FIELDS
    limit: jnp.ndarray
    burst: jnp.ndarray
    rem_i: jnp.ndarray
    flags: jnp.ndarray
    dur_lo: jnp.ndarray
    dur_hi: jnp.ndarray
    stamp_lo: jnp.ndarray
    stamp_hi: jnp.ndarray
    exp_lo: jnp.ndarray
    exp_hi: jnp.ndarray
    remf_hi: jnp.ndarray
    remf_lo: jnp.ndarray

    @property
    def bucket_k(self) -> int:
        return self.pfp_lo.shape[-1]

    @property
    def capacity(self) -> int:
        return self.pfp_lo.shape[-2] * self.pfp_lo.shape[-1]


def new_table(capacity: int, k: int = 8) -> Table:
    """Fresh empty table. `capacity` is rounded up to a multiple of the bucket
    width `k` (the analog of the reference's CacheSize, default 50_000,
    reference config.go:151); keep load factor ≤ ~0.5 for healthy buckets."""
    if capacity <= 0:
        raise ValueError("capacity must be positive")
    nb = max(1, -(-capacity // k))
    probe = lambda: jnp.zeros((nb, k), dtype=jnp.float32)
    flat = lambda: jnp.zeros(nb * k, dtype=jnp.float32)
    return Table(
        pfp_lo=probe(),
        pfp_hi=probe(),
        pexp_c=probe(),
        **{f: flat() for f in APPLY_FIELDS},
    )


def live_count(table, now_ms: int) -> int:
    """Number of live (non-empty, unexpired) slots — the analog of the
    reference cache Size() (lrucache.go:152-157). Uses the exact expiry from
    the apply plane. Accepts either table generation."""
    if not isinstance(table, Table):  # v2 packed-row table
        from gubernator_tpu.ops.table2 import live_count2

        return live_count2(table, now_ms)
    lo = np.asarray(table.pfp_lo).view(np.int32).reshape(-1)
    hi = np.asarray(table.pfp_hi).view(np.int32).reshape(-1)
    exp = np.asarray(table.exp_lo).view(np.int32).astype(np.int64) & 0xFFFFFFFF
    exp |= np.asarray(table.exp_hi).view(np.int32).astype(np.int64) << 32
    nonempty = (lo != 0) | (hi != 0)
    return int((nonempty & (exp >= now_ms)).sum())
