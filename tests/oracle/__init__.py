"""v1 decision kernel, demoted to a differential test oracle.

This was the round-1 production kernel (15 f32-carrier plane scatters); the
round-2 packed-row kernel (gubernator_tpu/ops/kernel2.py) replaced it on every
production path after real-TPU measurements (exp/exp_mem*.py, ~4x faster).
It is kept here because the reference-semantics suites were originally
validated against it, making it an independent implementation to diff v2
against on randomized traffic (tests/test_kernel2.py).
"""

from tests.oracle.kernel_v1 import decide as decide_v1
from tests.oracle.table_v1 import new_table as new_table_v1


def v1_engine(capacity: int, **kw):
    """A LocalEngine running the v1 oracle kernel."""
    from gubernator_tpu.ops.engine import LocalEngine

    return LocalEngine(
        capacity=capacity,
        decide_fn=decide_v1,
        table=new_table_v1(capacity),
        **kw,
    )
