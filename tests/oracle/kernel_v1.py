"""The vectorized rate-limit decision kernel.

One call replaces the reference's whole per-request inner stack — worker
channel → LRU map lookup → token/leaky bucket state machine (reference
workers.go:195-330 → lrucache.go:88-128 → algorithms.go:37-492) — with a single
jitted batch update over the HBM table:

    table', responses, stats = decide(table, batch)

Memory-op discipline: on TPU under the X64-emulation pass, 64-bit and
row-scatter memory ops serialize (≈20 ms per 128K rows), while 32-bit flat
scatters and narrow row gathers vectorize (<2 ms). The kernel therefore touches
HBM only through:
 1. probe  — three (B, K) row gathers of the bucket's probe plane (fingerprint
             halves + coarse expiry); all classification is fused elementwise.
 2. claim  — an auction over bucket lanes: each inserting row bids an int32
             priority ``(round ⋅ 2^24) | perm(row)`` on one free lane per round
             (lane choice hashed per row to spread contention), with owner rows
             pre-stamping their lanes at top priority. One flat scatter-max and
             one row gather per round; priorities are unique (odd-multiplier
             bijection on row ids) and monotone in round, so winners are exact
             and never displaced. Rows that lose every round are answered but
             not persisted (stats.dropped — the engine retries them in a
             follow-up dispatch; the reference's LRU would thrash instead,
             lrucache.go:138-149).
 3. apply  — twelve flat f32-carrier gathers of the winning slot's state;
             branchless token + leaky bucket math under masks, reproducing the
             exact decision tables of reference algorithms.go (per-step
             citations inline).
 4. write  — fifteen flat f32-carrier scatters (probe + apply planes).

Eviction: when a bucket has no vacant lane, the soonest-expiring lane (coarse
expiry order) is the bid target — expiry-stamp eviction, counted as the
reference's "unexpired eviction" alarm (lrucache.go:138-149).

Expiry: the probe plane's coarse (~1 s) expiry is used only conservatively
(reclaim clearly-dead lanes, order evictions); the authoritative
millisecond-exact `ExpireAt < now` check (reference cache.go:43-57) happens in
apply against the exact stored expiry, with `created_at` as "now" — the front
door stamps it at ingress, and tests get frozen time for free.

Correctness contract: fingerprints must be unique among active rows (the pass
planner, ops/plan.py, guarantees it). This reproduces the reference's per-key
serialization: gubernator's worker hash-ring ensures same-key requests apply
sequentially (workers.go:185-189); here "sequentially" = "in separate passes".

Deliberate divergences from the reference (documented, not cargo-culted):
* New-item leaky-bucket rate under DURATION_IS_GREGORIAN uses the Gregorian
  interval length, where the reference divides by the raw enum value
  (algorithms.go:438-449) yielding a nonsense reset_time (SURVEY.md §7).
* `limit`/`burst` must fit int32 (validated at the front door); stored token
  remaining saturates at int32.
* The leaky float64 remainder is stored double-single (two f32, ~48-bit
  mantissa) — exact for any realistic token count.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.batch import BatchStats, InstallBatch, ReqBatch, RespBatch
from gubernator_tpu.ops.math import StoredState, bucket_math
from tests.oracle.table_v1 import EXPC_SHIFT, Table
from gubernator_tpu.types import Algorithm, Behavior, Status

_CLAIM_ROUNDS = 2  # bidding rounds; engine retries dropped rows host-side
_MIX = 2654435761  # odd ⇒ (row * _MIX) mod 2^24 is a bijection (unique prios)

i64 = jnp.int64
i32 = jnp.int32
f64 = jnp.float64
f32 = jnp.float32


def _as_i32(x):
    return jax.lax.bitcast_convert_type(x, i32)


def _as_f32(x):
    return jax.lax.bitcast_convert_type(x, f32)


def _join64(lo32, hi32):
    return (hi32.astype(i64) << 32) | (lo32.astype(i64) & 0xFFFFFFFF)


def _lo32(x):
    return (x & 0xFFFFFFFF).astype(i32)


def _hi32(x):
    return (x >> 32).astype(i32)


def _probe_claim(table: Table, fp, now, active):
    """Shared probe + claim phases: find each row's slot (existing fingerprint
    match, vacant lane, or eviction victim). Returns
    (slot, owns, resolved, dropped, won_evict, my_lo, my_hi)."""
    NB, K = table.pfp_lo.shape
    C = NB * K
    B = fp.shape[0]
    if B > (1 << 20):
        raise ValueError("batch larger than 2^20 rows")

    # ------------------------------------------------------------------ probe
    bucket = (fp % NB).astype(i32)
    my_lo = _lo32(fp)
    my_hi = _hi32(fp)
    bfp_lo = _as_i32(table.pfp_lo[bucket])  # (B, K) row gathers
    bfp_hi = _as_i32(table.pfp_hi[bucket])
    bexp_c = _as_i32(table.pexp_c[bucket])

    offs = jnp.arange(K, dtype=i32)
    rows = jnp.arange(B, dtype=i32)
    emptyK = (bfp_lo == 0) & (bfp_hi == 0)
    fpm = (
        (bfp_lo == my_lo[:, None])
        & (bfp_hi == my_hi[:, None])
        & ~emptyK
        & active[:, None]
    )
    owns = fpm.any(axis=1)
    own_j = jnp.argmax(fpm, axis=1)

    now_c = (now >> EXPC_SHIFT).astype(i32)
    # conservative: only clearly-dead lanes count as vacant at probe level;
    # the exact ms expiry check happens in apply.
    probe_dead = bexp_c < (now_c[:, None] - 1)
    vacantK = emptyK | probe_dead

    # ------------------------------------------------------------------ claim
    DROPC = jnp.int32(C)
    need = active & ~owns
    mix24 = ((rows.astype(i64) * _MIX) & 0xFFFFFF).astype(i32)
    bids = jnp.zeros(C, dtype=i32)
    own_slot = bucket * K + own_j
    prio_own = ((_CLAIM_ROUNDS + 1) << 24) | mix24
    bids = bids.at[jnp.where(owns, own_slot, DROPC)].max(prio_own, mode="drop")

    evict_j = jnp.argmin(bexp_c, axis=1)
    any_vacant = vacantK.any(axis=1)

    lane_sel = own_j
    resolved = owns
    won_evict = jnp.zeros(B, dtype=bool)
    pending = jnp.zeros(B, dtype=bool)
    pend_lane = jnp.zeros(B, dtype=i32)
    pend_prio = jnp.zeros(B, dtype=i32)
    pend_evict = jnp.zeros(B, dtype=bool)
    # hashed lane preference spreads same-bucket contenders across lanes
    lane_score = ((rows[:, None] * _MIX + (offs[None, :] + 1) * 40503) & 0x7FFF) + 1
    for r in range(_CLAIM_ROUNDS + 1):
        bids_row = bids.reshape(NB, K)[bucket]  # (B, K) row gather
        if r > 0:
            at = jnp.take_along_axis(bids_row, pend_lane[:, None], axis=1)[:, 0]
            win = pending & (at == pend_prio)
            lane_sel = jnp.where(win, pend_lane, lane_sel)
            resolved = resolved | win
            won_evict = won_evict | (win & pend_evict)
        if r < _CLAIM_ROUNDS:
            free = vacantK & (bids_row == 0)
            has_free = free.any(axis=1)
            pick = jnp.argmax(jnp.where(free, lane_score, 0), axis=1)
            evict_bid = jnp.take_along_axis(bids_row, evict_j[:, None], axis=1)[:, 0]
            can_evict = ~any_vacant & (evict_bid == 0)
            lane = jnp.where(has_free, pick, evict_j)
            trying = need & ~resolved & (has_free | can_evict)
            prio = ((_CLAIM_ROUNDS - r) << 24) | mix24
            bids = bids.at[jnp.where(trying, bucket * K + lane, DROPC)].max(
                prio, mode="drop"
            )
            pending = trying
            pend_lane = lane
            pend_prio = prio
            pend_evict = trying & ~has_free

    slot = bucket * K + lane_sel  # always in range; meaningless if unresolved
    dropped = active & ~resolved
    return slot, owns, resolved, dropped, won_evict, my_lo, my_hi


def decide_impl(table: Table, req: ReqBatch) -> Tuple[Table, RespBatch, BatchStats]:
    """Un-jitted kernel body — call through `decide` (jitted, donating) on a
    single device, or directly inside shard_map (parallel/sharded.py)."""
    NB, K = table.pfp_lo.shape
    C = NB * K
    B = req.fp.shape[0]
    DROPC = jnp.int32(C)

    now = req.created_at  # per-row "now" (epoch ms)
    active = req.active
    slot, owns, resolved, dropped, won_evict, my_lo, my_hi = _probe_claim(
        table, req.fp, now, active
    )

    # ------------------------------------------------------------------ apply
    g32 = lambda arr: _as_i32(arr[slot])  # flat f32-carrier gather + bitcast
    s_limit = g32(table.limit).astype(i64)
    s_burst = g32(table.burst).astype(i64)
    s_rem_i = g32(table.rem_i).astype(i64)
    s_flags = g32(table.flags)
    s_duration = _join64(g32(table.dur_lo), g32(table.dur_hi))
    s_stamp = _join64(g32(table.stamp_lo), g32(table.stamp_hi))
    s_exp = _join64(g32(table.exp_lo), g32(table.exp_hi))
    s_rem_f = table.remf_hi[slot].astype(f64) + table.remf_lo[slot].astype(f64)
    s_algo = s_flags & 0xFF
    s_status = s_flags >> 8

    # the reference's lazy expiry-on-read (cache.go:43-57), ms-exact
    exists = owns & (s_exp >= now)
    # an eviction only alarms if the victim was genuinely still live
    # (reference "unexpired evictions", lrucache.go:138-149) — won_evict rows
    # gathered the victim's state at `slot` before overwriting it
    evicted_unexpired = won_evict & (s_exp >= now)

    # branchless decision table (shared with kernel2) — ops/math.py
    d = bucket_math(
        StoredState(
            limit=s_limit, burst=s_burst, rem_i=s_rem_i, algo=s_algo,
            status=s_status, duration=s_duration, stamp=s_stamp, exp=s_exp,
            rem_f=s_rem_f,
        ),
        req,
        exists,
    )
    rem_i_out, rem_f_out = d.rem_i_out, d.rem_f_out
    stamp_out, dur_out, exp_out = d.stamp_out, d.dur_out, d.exp_out
    burst_out, flags_out = d.burst_out, d.flags_out
    OVER = jnp.int32(int(Status.OVER_LIMIT))
    UNDER = jnp.int32(int(Status.UNDER_LIMIT))

    # token RESET_REMAINING removes the item: write back an empty slot
    fp_lo_out = jnp.where(d.remove, 0, my_lo)
    fp_hi_out = jnp.where(d.remove, 0, my_hi)
    expc_out = jnp.where(d.remove, 0, (exp_out >> EXPC_SHIFT).astype(i32))

    w = jnp.where(active & resolved, slot, DROPC)
    sat32 = lambda x: jnp.clip(x, -(2**31), 2**31 - 1).astype(i32)
    remf_hi_out = rem_f_out.astype(f32)
    remf_lo_out = (rem_f_out - remf_hi_out.astype(f64)).astype(f32)
    put = lambda arr, v: arr.reshape(-1).at[w].set(v, mode="drop").reshape(arr.shape)
    table = Table(
        pfp_lo=put(table.pfp_lo, _as_f32(fp_lo_out)),
        pfp_hi=put(table.pfp_hi, _as_f32(fp_hi_out)),
        pexp_c=put(table.pexp_c, _as_f32(expc_out)),
        limit=put(table.limit, _as_f32(sat32(req.limit))),
        burst=put(table.burst, _as_f32(sat32(burst_out))),
        rem_i=put(table.rem_i, _as_f32(sat32(rem_i_out))),
        flags=put(table.flags, _as_f32(flags_out)),
        dur_lo=put(table.dur_lo, _as_f32(_lo32(dur_out))),
        dur_hi=put(table.dur_hi, _as_f32(_hi32(dur_out))),
        stamp_lo=put(table.stamp_lo, _as_f32(_lo32(stamp_out))),
        stamp_hi=put(table.stamp_hi, _as_f32(_hi32(stamp_out))),
        exp_lo=put(table.exp_lo, _as_f32(_lo32(exp_out))),
        exp_hi=put(table.exp_hi, _as_f32(_hi32(exp_out))),
        remf_hi=put(table.remf_hi, remf_hi_out),
        remf_lo=put(table.remf_lo, remf_lo_out),
    )

    resp = RespBatch(
        status=jnp.where(active, d.resp_status, UNDER),
        limit=jnp.where(active, req.limit, i64(0)),
        remaining=jnp.where(active, d.resp_rem, i64(0)),
        reset_time=jnp.where(active, d.resp_reset, i64(0)),
        cache_hit=exists,
        dropped=dropped,
    )
    stats = BatchStats(
        cache_hits=exists.sum(dtype=i64),
        cache_misses=(active & ~exists).sum(dtype=i64),
        over_limit=(active & (resp.status == OVER)).sum(dtype=i64),
        evicted_unexpired=evicted_unexpired.sum(dtype=i64),
        dropped=dropped.sum(dtype=i64),
    )
    return table, resp, stats


decide = partial(jax.jit, donate_argnums=(0,))(decide_impl)


def install_impl(table: Table, inst: "InstallBatch") -> Tuple[Table, jnp.ndarray]:
    """Install owner-authoritative statuses into a (replica) table — the
    analog of UpdatePeerGlobals (reference gubernator.go:434-474): each entry
    unconditionally becomes a fresh item with ExpireAt = reset_time; token
    items keep the owner's remaining/status with CreatedAt = now; leaky items
    take Remaining = remaining, Burst = Limit, UpdatedAt = now.

    Returns (table', installed_mask)."""
    now = inst.now
    active = inst.active
    slot, owns, resolved, dropped, _evict, my_lo, my_hi = _probe_claim(
        table, inst.fp, now, active
    )
    NB, K = table.pfp_lo.shape
    DROPC = jnp.int32(NB * K)

    is_token = inst.algo == int(Algorithm.TOKEN_BUCKET)
    status_out = inst.status
    flags_out = inst.algo | (status_out << 8)
    rem_i_out = jnp.where(is_token, inst.remaining, i64(0))
    rem_f_out = jnp.where(is_token, f64(0.0), inst.remaining.astype(f64))
    burst_out = jnp.where(is_token, i64(0), inst.limit)
    exp_out = inst.reset_time

    w = jnp.where(active & resolved, slot, DROPC)
    sat32 = lambda x: jnp.clip(x, -(2**31), 2**31 - 1).astype(i32)
    put = lambda arr, v: arr.reshape(-1).at[w].set(v, mode="drop").reshape(arr.shape)
    table = Table(
        pfp_lo=put(table.pfp_lo, _as_f32(my_lo)),
        pfp_hi=put(table.pfp_hi, _as_f32(my_hi)),
        pexp_c=put(table.pexp_c, _as_f32((exp_out >> EXPC_SHIFT).astype(i32))),
        limit=put(table.limit, _as_f32(sat32(inst.limit))),
        burst=put(table.burst, _as_f32(sat32(burst_out))),
        rem_i=put(table.rem_i, _as_f32(sat32(rem_i_out))),
        flags=put(table.flags, _as_f32(flags_out)),
        dur_lo=put(table.dur_lo, _as_f32(_lo32(inst.duration))),
        dur_hi=put(table.dur_hi, _as_f32(_hi32(inst.duration))),
        stamp_lo=put(table.stamp_lo, _as_f32(_lo32(now))),
        stamp_hi=put(table.stamp_hi, _as_f32(_hi32(now))),
        exp_lo=put(table.exp_lo, _as_f32(_lo32(exp_out))),
        exp_hi=put(table.exp_hi, _as_f32(_hi32(exp_out))),
        remf_hi=put(table.remf_hi, rem_f_out.astype(f32)),
        remf_lo=put(
            table.remf_lo, (rem_f_out - rem_f_out.astype(f32).astype(f64)).astype(f32)
        ),
    )
    return table, active & resolved


install = partial(jax.jit, donate_argnums=(0,))(install_impl)

