"""Pure-Python reference oracles for the extended in-kernel algorithms.

One class per algorithm, dict-of-key state, integer-millisecond arithmetic
mirroring the masked decision tables in ops/math.py EXACTLY (same rounding,
same clamps, same expiry rules) — the parity contract every device
implementation (local + 8-dev mesh, full + compact wire) is tested against
in tests/test_algorithms.py. The token/leaky oracles live in
tests/oracle/kernel_v1.py (the v1 plane kernel); these cover the ISSUE-10
extensions: GCRA, sliding-window counters, concurrency leases.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple


def _clip(v: int, lo: int, hi: int) -> int:
    return max(lo, min(v, hi))


@dataclass
class GcraOracle:
    """Virtual scheduling: one theoretical-arrival-time (TAT) per key.

    T = duration // limit (ms per token), tau = T * burst. State is
    self-expiring — once now >= TAT the bucket is indistinguishable from a
    fresh one, which is exactly how the kernel's ExpireAt = TAT interacts
    with lazy expiry, so the oracle needs no explicit expiry handling:
    max(TAT, now) covers both."""

    tat: Dict[int, int] = field(default_factory=dict)

    def check(
        self, key: int, now: int, hits: int, limit: int, duration: int,
        burst: int = 0, drain: bool = False,
    ) -> Tuple[int, int, int]:
        burst = burst or limit
        T = max(duration // max(limit, 1), 1)
        tau = T * burst
        stored = self.tat.get(key)
        if hits < 0 and (stored is None or stored < now):
            # miss-release (ops/math.py neg_miss): a return against a key
            # with no live TAT removes instead of installing — full
            # bucket, reset 0
            self.tat.pop(key, None)
            return (0, burst, 0)
        tat0 = max(self.tat.get(key, now), now)
        # releases rewind the TAT but never below now (the GCRA analog of
        # the token clamp at `limit`)
        tat1 = max(tat0 + hits * T, now)
        deny = hits > 0 and tat1 - tau > now
        if deny:
            out = now + tau if drain else tat0
        else:
            out = tat1
        self.tat[key] = out
        rem = _clip((now + tau - out) // T, 0, burst)
        reset = out - tau + T * limit
        if deny and not drain:
            # exact conforming instant for the denied request (the
            # TAT-derived retry_after bound, ops/math.py gcra_lanes)
            reset = tat1 - tau
        return (1 if deny else 0, rem, reset)


@dataclass
class SlidingWindowOracle:
    """Previous+current window interpolation; windows align to duration
    boundaries. State: (window_start, current_count, previous_count)."""

    state: Dict[int, Tuple[int, int, int]] = field(default_factory=dict)

    def check(
        self, key: int, now: int, hits: int, limit: int, duration: int,
        drain: bool = False,
    ) -> Tuple[int, int, int]:
        dur = max(duration, 1)
        ws = now - now % dur
        s_ws, s_cur, s_prev = self.state.get(key, (None, 0, 0))
        if hits < 0 and (s_ws is None or now >= s_ws + 2 * dur):
            # miss-release: the slot (exp = ws + 2·dur) is gone — remove,
            # never install fresh state from a return (ops/math.py)
            self.state.pop(key, None)
            return (0, limit, 0)
        if s_ws == ws:
            cur, prev = s_cur, s_prev
        elif s_ws == ws - dur:
            cur, prev = 0, s_cur
        else:  # stale beyond one window (== the slot's ws+2dur expiry)
            cur, prev = 0, 0
        used = cur + (prev * (dur - (now - ws))) // dur
        deny = hits > 0 and used + hits > limit
        take = 0 if (deny and not drain) else hits
        # releases clamp at an empty window — a return can never drive the
        # stored count negative (remaining past `limit`)
        cur = max(cur + take, 0)
        self.state[key] = (ws, cur, prev)
        rem = _clip(limit - (used + take), 0, limit)
        return (1 if deny else 0, rem, ws + dur)


@dataclass
class LeaseOracle:
    """Concurrency leases: hits>0 acquires, hits<0 releases, 0 queries.
    State: (inflight, expire_at); an expired slot reclaims every lease —
    the TTL-eviction reclamation contract."""

    state: Dict[int, Tuple[int, int]] = field(default_factory=dict)

    def check(
        self, key: int, now: int, hits: int, limit: int, duration: int,
        drain: bool = False,
    ) -> Tuple[int, int, int]:
        inflight, exp = self.state.get(key, (0, None))
        if exp is None or exp < now:  # lazy expiry (exp >= now keeps it live)
            inflight, exp = 0, None
        if hits < 0 and exp is None:
            # miss-release: a late release after TTL reclamation (or of a
            # never-seen key) removes instead of installing — the
            # miss-safety rule (ops/math.py neg_miss)
            self.state.pop(key, None)
            return (0, limit, 0)
        deny = hits > 0 and inflight + hits > limit
        take = 0 if (deny and not drain) else hits
        inflight = max(inflight + take, 0)
        refresh = hits > 0 and not (deny and not drain)
        if refresh or exp is None:
            exp = now + duration
        self.state[key] = (inflight, exp)
        rem = _clip(limit - inflight, 0, limit)
        return (1 if deny else 0, rem, exp)


class TokenOracle:
    """Minimal fixed-window token bucket (the reference's semantics for the
    cases the GCRA-equivalence test drives: constant config, hits>0, no
    behaviors): remaining decrements, resets when the item expires."""

    def __init__(self):
        self.state: Dict[int, Tuple[int, int]] = {}  # key -> (rem, exp)

    def check(self, key, now, hits, limit, duration) -> Tuple[int, int]:
        rem, exp = self.state.get(key, (None, None))
        if rem is None or exp < now:
            rem, exp = limit, now + duration
            # new item (go:202-252)
            if hits > limit:
                self.state[key] = (limit, exp)
                return 1, limit
            self.state[key] = (limit - hits, exp)
            return 0, limit - hits
        if rem == 0 and hits > 0:
            self.state[key] = (rem, exp)
            return 1, rem
        if hits > rem:
            return 1, rem
        self.state[key] = (rem - hits, exp)
        return 0, rem - hits
