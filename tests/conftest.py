"""Test fixture: force a virtual 8-device CPU platform before jax initializes.

Multi-chip sharding (parallel/) is exercised on a host-platform mesh exactly as
the reference exercises its cluster in-process (reference cluster/cluster.go
boots N daemons in one test binary); real-TPU behavior is covered by the
driver's bench/dryrun entry points.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# persistent kernel-compile cache: the suite compiles a handful of batch-shape
# variants of the decision kernel; cache them across runs
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/gubernator_tpu_jit_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

# The axon bootstrap (sitecustomize in /root/.axon_site) force-sets
# jax_platforms to the TPU tunnel; tests run on the virtual CPU mesh, so
# override it back *after* jax import, before any backend initialization.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def frozen_now() -> int:
    """A fixed epoch-ms 'now' — the analog of holster/clock frozen time
    (reference Makefile:20 -tags holster_test_mode). The kernel takes time from
    request.created_at, so tests simply pass timestamps."""
    return 1_700_000_000_000
