"""Observability-layer tests (ISSUE 7): device-side table telemetry parity
vs the host oracle (local + 8-dev CPU mesh), OpenMetrics exemplars whose
trace_ids resolve to dispatch spans, span links across a coalesced flush,
the /v1/debug/* JSON plane, and GLOBAL sync-staleness monotonicity."""

import asyncio
import functools

import numpy as np
import pytest

from gubernator_tpu import tracing
from gubernator_tpu.client import V1Client
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.telemetry import (
    REMAIN_EDGES,
    TTL_EDGES_MS,
    finish_scan,
    host_telemetry,
)
from gubernator_tpu.types import RateLimitRequest

from tests.cluster import daemon_config

NOW = 1_700_000_000_000

PARITY_FIELDS = (
    "live_keys", "occupied_slots", "over_keys", "bucket_occupancy",
    "ttl_horizon", "remaining_frac", "block_fill",
)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def _mixed_cols(rng, n):
    """Traffic that exercises every telemetry dimension: token+leaky, tight
    limits (depleted + OVER keys), short durations (expired slots at a later
    scan now), and spread TTL horizons."""
    fp = np.unique(rng.integers(1, (1 << 63) - 1, size=2 * n,
                                dtype=np.int64))[:n]
    return RequestColumns(
        fp=fp,
        algo=(np.arange(n) % 2).astype(np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=rng.integers(0, 5, n).astype(np.int64),
        limit=rng.integers(1, 10, n).astype(np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=rng.choice(
            [500, 30_000, 120_000, 7_200_000, 172_800_000], n
        ).astype(np.int64),
        created_at=np.full(n, NOW, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


class StubExporter:
    """In-memory tracing exporter: records what the OTLP one would POST."""

    def __init__(self):
        self.spans = []
        self.exported = 3
        self.dropped = 1
        self.export_errors = 0

    def record(self, name, span, parent_span_id, start_ns, end_ns,
               attributes=None, links=(), kind=2):
        self.spans.append({
            "name": name, "trace_id": span.trace_id, "span_id": span.span_id,
            "parent": parent_span_id, "start": start_ns, "end": end_ns,
            "attributes": dict(attributes or {}), "links": list(links),
            "kind": kind,
        })

    def flush(self):
        pass


# ---------------------------------------------------------------- telemetry


def test_telemetry_scan_matches_host_oracle_local():
    eng = LocalEngine(capacity=4096, write_mode="xla")
    rng = np.random.default_rng(11)
    eng.check_columns(_mixed_cols(rng, 3000), now_ms=NOW)
    # drive a couple of keys to exact depletion so stored OVER status exists
    hot = RequestColumns(
        fp=np.asarray([12345], dtype=np.int64),
        algo=np.zeros(1, np.int32), behavior=np.zeros(1, np.int32),
        hits=np.asarray([3], np.int64), limit=np.asarray([3], np.int64),
        burst=np.zeros(1, np.int64), duration=np.asarray([60_000], np.int64),
        created_at=np.full(1, NOW, np.int64), err=np.zeros(1, np.int8),
    )
    eng.check_columns(hot, now_ms=NOW)  # depletes to remaining=0
    # a hit against a depleted key is what sticks stored status = OVER
    eng.check_columns(hot._replace(hits=np.asarray([1], np.int64)),
                      now_ms=NOW)
    later = NOW + 2_000  # the 500 ms-duration cohort is expired by now
    snap = finish_scan(eng.telemetry_begin(later))
    oracle = host_telemetry(np.asarray(eng.table.rows), later)
    for f in PARITY_FIELDS:
        assert getattr(snap, f) == getattr(oracle, f), f
    # structural invariants the dashboards rely on
    assert snap.over_keys >= 1  # the depleted key
    assert snap.occupied_slots > snap.live_keys  # expired cohort visible
    assert sum(snap.bucket_occupancy) == snap.n_buckets
    assert sum(snap.probe_depth) == snap.live_keys
    assert sum(snap.block_fill) == snap.n_buckets // min(64, snap.n_buckets) \
        or sum(snap.block_fill) > 0
    assert snap.ttl_horizon == sorted(snap.ttl_horizon)  # cumulative
    assert snap.remaining_frac == sorted(snap.remaining_frac)
    assert snap.ttl_horizon[-1] <= snap.live_keys
    assert len(snap.ttl_horizon) == len(TTL_EDGES_MS)
    assert len(snap.remaining_frac) == len(REMAIN_EDGES)


def test_telemetry_scan_matches_host_oracle_sharded():
    from gubernator_tpu.parallel import make_mesh
    from gubernator_tpu.parallel.sharded import ShardedEngine

    eng = ShardedEngine(make_mesh(8), capacity_per_shard=1 << 10,
                        write_mode="xla")
    rng = np.random.default_rng(13)
    eng.check_columns(_mixed_cols(rng, 4000), now_ms=NOW)
    later = NOW + 2_000
    snap = finish_scan(eng.telemetry_begin(later))
    oracle = host_telemetry(np.asarray(eng.table.rows), later)
    for f in PARITY_FIELDS:
        assert getattr(snap, f) == getattr(oracle, f), f
    # the mesh variant additionally reports per-shard live counts
    assert snap.per_shard_live is not None and len(snap.per_shard_live) == 8
    assert sum(snap.per_shard_live) == snap.live_keys
    assert snap.capacity == 8 * (1 << 10)


@async_test
async def test_daemon_telemetry_loop_populates_metrics():
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.metrics import parse_metrics

    conf = daemon_config(telemetry_interval_ms=100.0)
    d = await Daemon.spawn(conf)
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits([
            RateLimitRequest(name="tm", unique_key=f"k{i}", hits=1,
                             limit=100, duration=60_000)
            for i in range(64)
        ])
        for _ in range(50):
            await asyncio.sleep(0.1)
            if d._table_telemetry is not None:
                break
        assert d._table_telemetry is not None, "telemetry loop never ticked"
        scraped = parse_metrics(d.metrics.render().decode())
        assert scraped["gubernator_tpu_table_live_keys"][()] == 64
        assert scraped["gubernator_tpu_table_capacity"][()] >= 8192
        occ = scraped["gubernator_tpu_table_bucket_occupancy"]
        assert sum(occ.values()) == d._table_telemetry.n_buckets
        # snapshot histograms carry an explicit +Inf bound = live keys
        assert scraped["gubernator_tpu_table_ttl_horizon"][
            (("le", "+Inf"),)
        ] == 64
        assert scraped["gubernator_tpu_table_scan_duration_count"][()] >= 1
        # the exporter-health satellites render (zeros without an exporter)
        assert "gubernator_otel_spans_exported_total" in scraped
        assert "gubernator_global_sync_staleness_seconds" in scraped
    finally:
        await client.close()
        await d.close()


# ------------------------------------------------- exemplars + span links


@async_test
async def test_stage_exemplars_resolve_to_dispatch_spans():
    """A scraped stage_duration bucket must carry an OpenMetrics exemplar
    whose trace_id resolves to a recorded `dispatch` span holding ≥1 request
    span link (the acceptance criterion's exact chain)."""
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families,
    )

    from gubernator_tpu.service.daemon import Daemon

    exp = StubExporter()
    old = tracing.exporter
    tracing.set_exporter(exp)
    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        reqs = [
            RateLimitRequest(name="ex", unique_key=f"k{i}", hits=1,
                             limit=100, duration=60_000)
            for i in range(32)
        ]
        await asyncio.gather(*(client.get_rate_limits(reqs)
                               for _ in range(4)))
        text = d.metrics.render(openmetrics=True).decode()
        exemplars = {}  # metric name -> [trace_id]
        for fam in text_string_to_metric_families(text):
            for s in fam.samples:
                if s.exemplar is not None:
                    exemplars.setdefault(s.name, []).append(
                        s.exemplar.labels["trace_id"]
                    )
        # stage buckets AND the (Summary→Histogram satellite) request plane
        assert any(k.startswith("gubernator_tpu_stage_duration_bucket")
                   for k in exemplars), exemplars.keys()
        assert any(
            k.startswith("gubernator_grpc_request_duration_bucket")
            for k in exemplars
        ), exemplars.keys()
        for tid in {t for v in exemplars.values() for t in v}:
            assert len(tid) == 32 and int(tid, 16)  # valid W3C trace id
        dispatches = {s["trace_id"]: s for s in exp.spans
                      if s["name"] == "dispatch"}
        assert dispatches, "no dispatch spans recorded"
        stage_tids = [
            t for k, v in exemplars.items()
            if k.startswith("gubernator_tpu_stage_duration_bucket")
            for t in v
        ]
        resolved = [dispatches[t] for t in stage_tids if t in dispatches]
        assert resolved, (stage_tids, list(dispatches))
        assert any(len(sp["links"]) >= 1 for sp in resolved)
        assert resolved[0]["attributes"]["batch.rows"] >= 32
        # stage child spans hang under the dispatch span
        stages = {s["name"] for s in exp.spans
                  if s["parent"] and s["trace_id"] in dispatches}
        assert {"queue", "put", "issue", "fetch"} <= stages
    finally:
        tracing.set_exporter(old)
        await client.close()
        await d.close()


@async_test
async def test_request_spans_link_to_shared_dispatch_span():
    """Requests coalesced into ONE flush each carry a link to the SAME
    dispatch span — the causality edge batching otherwise erases."""
    from gubernator_tpu.service.daemon import Daemon

    exp = StubExporter()
    old = tracing.exporter
    tracing.set_exporter(exp)
    # non-adaptive 50 ms window: concurrent requests land in one flush
    conf = daemon_config()
    conf.behaviors = BehaviorConfig(
        batch_wait_ms=50.0, adaptive_batch=False,
        batch_timeout_ms=5000.0, global_timeout_ms=5000.0,
    )
    d = await Daemon.spawn(conf)
    try:
        async def one(i):
            trace = f"{i:02d}" * 16
            await d.get_rate_limits([
                __import__("gubernator_tpu.proto.gubernator_pb2",
                           fromlist=["x"]).RateLimitReq(
                    name="ln", unique_key=f"k{i}", hits=1, limit=100,
                    duration=60_000,
                    metadata={"traceparent": f"00-{trace}-{'ab' * 8}-01"},
                )
            ])
            return trace

        traces = await asyncio.gather(*(one(i) for i in range(1, 5)))
        req_spans = [s for s in exp.spans if s["name"] == "GetRateLimits"
                     and s["trace_id"] in traces]
        assert len(req_spans) == 4
        linked_dispatches = [s["links"][0].span_id for s in req_spans
                             if s["links"]]
        assert linked_dispatches, "no request span carried a dispatch link"
        # at least two requests shared one flush → same dispatch span id
        assert any(linked_dispatches.count(x) >= 2
                   for x in set(linked_dispatches)), linked_dispatches
        # and the dispatch span links back to its member request spans
        disp = {s["span_id"]: s for s in exp.spans if s["name"] == "dispatch"}
        shared = max(set(linked_dispatches), key=linked_dispatches.count)
        assert len(disp[shared]["links"]) >= 2
    finally:
        tracing.set_exporter(old)
        await d.close()


# --------------------------------------------------------------- debug plane


@async_test
async def test_debug_endpoints_schema():
    import aiohttp

    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config(telemetry_interval_ms=0.0))
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits([
            RateLimitRequest(name="dbg", unique_key=f"k{i}", hits=1,
                             limit=10, duration=60_000)
            for i in range(8)
        ])
        base = f"http://{d.conf.http_address}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/v1/debug/table") as r:
                assert r.status == 200
                table = await r.json()
            async with s.get(f"{base}/v1/debug/pipeline") as r:
                pipeline = await r.json()
            async with s.get(f"{base}/v1/debug/peers") as r:
                peers = await r.json()
            async with s.get(f"{base}/v1/debug/global") as r:
                glob = await r.json()
            async with s.get(f"{base}/v1/debug/bogus") as r:
                assert r.status == 404
        # table: scans on demand when the loop is disabled
        assert table["live_keys"] == 8
        assert set(table) >= {
            "capacity", "load_factor", "bucket_occupancy", "probe_depth",
            "ttl_horizon_ms", "remaining_frac", "block_fill_deciles",
            "over_fraction", "scan_ms",
        }
        b = pipeline["batcher"]
        assert set(b) >= {
            "pending_rows", "workers", "workers_alive", "inflight",
            "fused_dispatches", "column_dispatches", "adaptive_closes",
            "close_reasons",
        }
        assert set(b["close_reasons"]) == {"rows", "bytes", "idle", "slot"}
        assert pipeline["engine"]["kind"] == "LocalEngine"
        assert peers["self"] == d.conf.advertise_address
        assert set(peers["handoff"]) >= {"enabled", "active", "rounds"}
        assert "staleness_s" in glob and "manager" in glob
        assert set(glob["manager"]) >= {
            "pending_hits", "oldest_hit_age_s", "unsynced_keys",
        }
    finally:
        await client.close()
        await d.close()


@async_test
async def test_debug_endpoints_disabled_by_config():
    import aiohttp

    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config(debug_endpoints=False))
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{d.conf.http_address}/v1/debug/table"
            ) as r:
                assert r.status == 404
    finally:
        await d.close()


# ---------------------------------------------------------------- staleness


def test_pending_hits_age_monotonic_and_cleared():
    import time as _time

    from gubernator_tpu.ops.batch import HostBatch, pack_columns
    from gubernator_tpu.parallel.global_sync import PendingHits

    rng = np.random.default_rng(3)
    cols = _mixed_cols(rng, 8)
    hb, _err = pack_columns(cols, NOW)
    p = PendingHits()
    assert p.age_s() == 0.0
    p.merge(hb, np.arange(8), np.ones(8, dtype=np.int64),
            np.zeros(8, dtype=np.int32))
    a1 = p.age_s()
    _time.sleep(0.02)
    a2 = p.age_s()
    assert a2 > a1 >= 0.0  # monotonic while un-drained
    p.take(3)  # partial drain keeps the (conservative) age
    assert p.age_s() >= a2
    p.take(100)  # full drain clears it
    assert p.age_s() == 0.0
    p.merge(hb, np.arange(8), np.ones(8, dtype=np.int64),
            np.zeros(8, dtype=np.int32))
    assert p.age_s() < a2  # re-anchored at the new first entry
    p.clear()
    assert p.age_s() == 0.0


@async_test
async def test_global_staleness_gauge_under_paused_sync():
    """With the sync loop effectively paused (huge GlobalSyncWait), queued
    GLOBAL hits age monotonically and the gauge reports it; a drained queue
    reads 0."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.metrics import parse_metrics

    conf = daemon_config()
    conf.behaviors = BehaviorConfig(
        global_sync_wait_ms=600_000.0,  # paused for this test's lifetime
        batch_timeout_ms=5000.0, global_timeout_ms=5000.0,
    )
    d = await Daemon.spawn(conf)
    try:
        assert d.global_sync_staleness_s() == 0.0
        item = pb.RateLimitReq(name="gs", unique_key="k", hits=2, limit=10,
                               duration=60_000)
        d.global_manager.queue_hit("gs_k", item)
        a1 = d.global_sync_staleness_s()
        await asyncio.sleep(0.05)
        a2 = d.global_sync_staleness_s()
        assert a2 > a1 >= 0.0
        # more hits on the SAME key do not reset the age
        d.global_manager.queue_hit("gs_k", item)
        assert d.global_sync_staleness_s() >= a2
        # the /metrics render refreshes the gauge
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://{d.conf.http_address}/metrics") as r:
                scraped = parse_metrics(await r.text())
        assert scraped["gubernator_global_sync_staleness_seconds"][()] >= a2
        # a successful drain (no peers → keys dropped) zeroes it
        await d.global_manager._send_hits()
        assert d.global_sync_staleness_s() == 0.0
    finally:
        await d.close()


# ------------------------------------------------------------ otel satellites


def test_exporter_from_env_resource_attributes():
    from gubernator_tpu.otel import exporter_from_env

    exp = exporter_from_env({
        "OTEL_EXPORTER_OTLP_ENDPOINT": "http://127.0.0.1:1",
        "OTEL_SERVICE_NAME": "svc-a",
        "OTEL_RESOURCE_ATTRIBUTES":
            "service.name=ignored,host.name=node-3,region=us%2Deast,bad",
    })
    try:
        assert exp.service_name == "svc-a"  # OTEL_SERVICE_NAME wins
        assert exp.resource_attributes == {
            "host.name": "node-3", "region": "us-east",
        }
        payload = exp._payload([{"traceId": "0" * 32, "spanId": "1" * 16,
                                 "name": "x", "kind": 2,
                                 "startTimeUnixNano": "1",
                                 "endTimeUnixNano": "2"}])
        import json

        attrs = json.loads(payload)["resourceSpans"][0]["resource"][
            "attributes"
        ]
        by_key = {a["key"]: a["value"] for a in attrs}
        assert by_key["service.name"] == {"stringValue": "svc-a"}
        assert by_key["host.name"] == {"stringValue": "node-3"}
        assert by_key["region"] == {"stringValue": "us-east"}
    finally:
        exp.close()

    # service.name from the resource attrs when OTEL_SERVICE_NAME is unset
    exp2 = exporter_from_env({
        "OTEL_EXPORTER_OTLP_ENDPOINT": "http://127.0.0.1:1",
        "OTEL_RESOURCE_ATTRIBUTES": "service.name=from-attrs",
    })
    try:
        assert exp2.service_name == "from-attrs"
        assert "service.name" not in exp2.resource_attributes
    finally:
        exp2.close()


def test_otel_span_counters_reflect_exporter():
    from gubernator_tpu.service.metrics import DaemonMetrics, parse_metrics

    exp = StubExporter()  # exported=3, dropped=1, export_errors=0
    old = tracing.exporter
    tracing.set_exporter(exp)
    try:
        m = DaemonMetrics()
        scraped = parse_metrics(m.render().decode())
        assert scraped["gubernator_otel_spans_exported_total"][()] == 3
        assert scraped["gubernator_otel_spans_dropped_total"][()] == 1
        assert scraped["gubernator_otel_spans_export_errors_total"][()] == 0
    finally:
        tracing.set_exporter(old)


def test_otlp_record_carries_attributes_and_links():
    from gubernator_tpu.otel import OTLPJsonExporter

    exp = OTLPJsonExporter("http://127.0.0.1:1")
    try:
        parent = tracing.new_span()
        link = tracing.new_span()
        exp.record("dispatch", parent, "", 1, 2,
                   attributes={"batch.rows": 42, "batch.fused": True,
                               "note": "x"},
                   links=[link], kind=1)
        entry = exp._buf[-1]
        assert entry["kind"] == 1
        by_key = {a["key"]: a["value"] for a in entry["attributes"]}
        assert by_key["batch.rows"] == {"intValue": "42"}
        assert by_key["batch.fused"] == {"boolValue": True}
        assert by_key["note"] == {"stringValue": "x"}
        assert entry["links"] == [
            {"traceId": link.trace_id, "spanId": link.span_id}
        ]
    finally:
        exp.close()


def test_pending_link_registry_bounded_and_popped():
    a, b = tracing.new_span(), tracing.new_span()
    tracing.add_span_link(a, b)
    tracing.add_span_link(a, b)
    assert len(tracing.take_span_links(a.span_id)) == 2
    assert tracing.take_span_links(a.span_id) == []  # popped
    tracing.add_span_link(None, b)  # no-ops never register
    tracing.add_span_link(a, None)
    assert tracing.take_span_links(a.span_id) == []
