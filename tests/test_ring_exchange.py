"""Pod-scale mesh suite: ring-exchange parity, (host, device) topology, and
the hierarchical GLOBAL sync.

The ring schedule (parallel/ring.py) must be BYTE-identical to the
`lax.all_to_all` oracle it replaces — at every mesh width, under both dedup
modes, through capacity overflow, and on the 2-D (host, device) topology.
The inter-slice compact sync codec (service/wire.sync_wire_pb) must
round-trip exactly and engage on the real gRPC peer plane.
"""

import asyncio
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from gubernator_tpu.ops.batch import columns_from_requests
from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.global_sync import GlobalShardedEngine
from gubernator_tpu.parallel.mesh import (
    devices_per_host,
    host_of_shard,
    mesh_hosts,
    shard_axes,
    shard_spec,
)
from gubernator_tpu.parallel.ring import a2a_impl, make_exchange_probe
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, MINUTE


def req(key, hits=1, limit=100, duration=MINUTE,
        algorithm=Algorithm.TOKEN_BUCKET, behavior=Behavior.BATCHING,
        created_at=None):
    return RateLimitRequest(
        name="ring", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algorithm, behavior=behavior,
        created_at=created_at,
    )


def canon(rows: np.ndarray) -> np.ndarray:
    """Sort each bucket's slots by fingerprint — canonical live state."""
    from gubernator_tpu.ops.table2 import F, K

    D, NB, _ = rows.shape
    s = rows.reshape(D, NB, K, F)
    key = (s[..., 1].astype(np.int64) << 32) | (
        s[..., 0].astype(np.int64) & 0xFFFFFFFF
    )
    order = np.argsort(key, axis=2, kind="stable")
    return np.take_along_axis(s, order[..., None], axis=2)


def assert_resp_equal(want, got, ctx=""):
    for i, (a, b) in enumerate(zip(want, got)):
        assert (a.status, a.remaining, a.reset_time, a.error) == (
            b.status, b.remaining, b.reset_time, b.error,
        ), f"{ctx} row {i}: {a} != {b}"


def mixed_corpus(rng, t, step, n=200, keys=70):
    ks = rng.integers(0, keys, size=n)
    return [
        req(
            f"m{k}",
            hits=1 + int(k) % 3,
            limit=1000,
            algorithm=(Algorithm.TOKEN_BUCKET if k % 3
                       else Algorithm.LEAKY_BUCKET),
            behavior=(Behavior.RESET_REMAINING if k % 11 == 1
                      else Behavior.BATCHING),
            created_at=t + step,
        )
        for k in ks
    ]


# --------------------------------------------------------------- topology


def test_make_mesh_topology():
    """(host, device) addressing: axes, host-major linearization, helper
    introspection, and the simulated-host env knob."""
    m1 = make_mesh(8)
    assert m1.axis_names == ("shard",)
    assert mesh_hosts(m1) == 1 and devices_per_host(m1) == 8
    assert shard_axes(m1) == "shard"

    m2 = make_mesh(8, hosts=2)
    assert m2.axis_names == ("host", "device")
    assert mesh_hosts(m2) == 2 and devices_per_host(m2) == 4
    assert shard_axes(m2) == ("host", "device")
    # host-major: shard s lives at grid position (s // dl, s % dl), and the
    # flat device order matches the 1-D mesh's — re-meshing moves no keys
    assert list(m2.devices.flat) == list(m1.devices.flat)
    np.testing.assert_array_equal(
        host_of_shard(m2, np.arange(8)), np.arange(8) // 4
    )

    with pytest.raises(ValueError):
        make_mesh(6, hosts=4)  # uneven split

    import os

    os.environ["GUBER_MESH_HOSTS"] = "4"
    try:
        m4 = make_mesh(8)
        assert mesh_hosts(m4) == 4 and devices_per_host(m4) == 2
    finally:
        del os.environ["GUBER_MESH_HOSTS"]


def test_a2a_impl_resolution(monkeypatch):
    assert a2a_impl("ring") == "ring"
    assert a2a_impl("collective") == "collective"
    monkeypatch.setenv("GUBER_A2A_IMPL", "ring")
    assert a2a_impl() == "ring"
    monkeypatch.setenv("GUBER_A2A_IMPL", "auto")
    # CPU backend: auto = collective (the seed lowering)
    assert a2a_impl() == "collective"
    monkeypatch.setenv("GUBER_A2A_IMPL", "bogus")
    with pytest.raises(ValueError):
        a2a_impl()


# --------------------------------------------------- exchange-level parity


@pytest.mark.parametrize("D", [2, 4, 8])
def test_exchange_parity_vs_collective(D):
    """ring.exchange == lax.all_to_all byte-for-byte at every mesh width,
    for both the 1-D and the (host, device) topology."""
    rng = np.random.default_rng(D)
    meshes = [make_mesh(D)]
    if D % 2 == 0:
        meshes.append(make_mesh(D, hosts=2))
    for mesh in meshes:
        block = (D, 5, 64)
        x = jnp.asarray(
            rng.integers(-(1 << 31), 1 << 31, size=(D,) + block, dtype=np.int64)
        )
        x = jax.device_put(x, NamedSharding(mesh, shard_spec(mesh)))
        got = np.asarray(make_exchange_probe(mesh, block, "ring")(x))
        want = np.asarray(make_exchange_probe(mesh, block, "collective")(x))
        np.testing.assert_array_equal(got, want, err_msg=f"D={D} {mesh.axis_names}")


def test_exchange_probe_truncated_hops():
    """A k-hop ring prefix delivers exactly the blocks within k hops (the
    per-hop bench probe's contract): hop slots outside the prefix are zero,
    inside it equal the full exchange."""
    D = 8
    mesh = make_mesh(D)
    rng = np.random.default_rng(3)
    block = (D, 4, 16)
    x = jnp.asarray(rng.integers(1, 1 << 30, size=(D,) + block, dtype=np.int64))
    x = jax.device_put(x, NamedSharding(mesh, shard_spec(mesh)))
    full = np.asarray(make_exchange_probe(mesh, block, "collective")(x))
    for hops in (1, 3):
        part = np.asarray(make_exchange_probe(mesh, block, "ring", hops=hops)(x))
        for d in range(D):
            for s in range(D):
                lag = (d - s) % D
                want = full[d, s] if lag <= hops else np.zeros_like(full[d, s])
                np.testing.assert_array_equal(part[d, s], want)


# ----------------------------------------------------- engine-level parity


@pytest.mark.parametrize("D", [2, 4, 8])
@pytest.mark.parametrize("dedup", ["host", "device"])
def test_ring_engine_parity(D, dedup, frozen_now):
    """route="device" through the ring schedule vs the collective oracle:
    responses, stats, and canonical live state identical over multi-step
    mixed traffic at every mesh width × dedup mode."""
    t = frozen_now
    mesh = make_mesh(D)
    ring = ShardedEngine(mesh, capacity_per_shard=2048, route="device",
                         dedup=dedup, a2a="ring")
    coll = ShardedEngine(mesh, capacity_per_shard=2048, route="device",
                         dedup=dedup, a2a="collective")
    rng = np.random.default_rng(D * 7 + (dedup == "device"))
    for step in range(3):
        reqs = mixed_corpus(rng, t, step, n=160)
        want = coll.check(reqs, now_ms=t + step)
        got = ring.check(reqs, now_ms=t + step)
        assert_resp_equal(want, got, f"D={D} dedup={dedup} step={step}")
    np.testing.assert_array_equal(canon(coll.snapshot()), canon(ring.snapshot()))
    assert coll.stats.cache_hits == ring.stats.cache_hits
    assert coll.stats.cache_misses == ring.stats.cache_misses
    assert coll.stats.over_limit == ring.stats.over_limit


def test_ring_zipf_overflow_parity(frozen_now):
    """Skewed batches through the exchange: Zipf duplicate traffic (route
    parity under dedup) plus a hash-concentrated batch that genuinely
    overflows one destination's pair capacity — the retry chain must make
    the schedule invisible (identical responses, zero errors) and the
    overflow must be OBSERVABLE via the engine's a2a_overflow counter (the
    gubernator_tpu_a2a_overflow_total source)."""
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.parallel.mesh import shard_of

    t = frozen_now
    mesh = make_mesh(8)
    ring = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                         dedup="device", a2a="ring")
    coll = ShardedEngine(mesh, capacity_per_shard=4096, route="device",
                         dedup="device", a2a="collective")
    rng = np.random.default_rng(17)
    z = np.minimum(rng.zipf(1.1, size=2048) - 1, 1023)
    reqs = [req(f"z{k}", hits=1, limit=1 << 20, created_at=t) for k in z]
    want = coll.check(reqs, now_ms=t)
    got = ring.check(reqs, now_ms=t)
    assert_resp_equal(want, got, "zipf")
    assert all(r.error == "" for r in got)

    # distinct keys all OWNED BY SHARD 0: every source block concentrates on
    # one destination, far past pair_capacity's 5σ multinomial bound
    hot = []
    i = 0
    while len(hot) < 800:
        if shard_of(np.int64(fingerprint("ring", f"h{i}")), 8) == 0:
            hot.append(f"h{i}")
        i += 1
    reqs = [req(k, hits=1, limit=1 << 20, created_at=t) for k in hot]
    want = coll.check(reqs, now_ms=t)
    got = ring.check(reqs, now_ms=t)
    assert_resp_equal(want, got, "hot-shard")
    assert all(r.error == "" for r in got)
    np.testing.assert_array_equal(canon(coll.snapshot()), canon(ring.snapshot()))
    # both schedules overflowed identically — and the take-delta drains once
    assert ring.a2a_overflow == coll.a2a_overflow > 0
    impl, d = ring.take_a2a_overflow_delta()
    assert impl == "ring" and d == ring.a2a_overflow
    assert ring.take_a2a_overflow_delta() == ("ring", 0)


def test_multihost_mesh_state_parity(frozen_now):
    """Re-meshing the same 8 devices from 1 host to 2 (host, device) rows
    moves no keys: identical responses and canonical state, ring exchange
    included — the ownership-stability contract of the host-major layout."""
    t = frozen_now
    one = ShardedEngine(make_mesh(8), capacity_per_shard=2048,
                        route="device", dedup="device", a2a="ring")
    two = ShardedEngine(make_mesh(8, hosts=2), capacity_per_shard=2048,
                        route="device", dedup="device", a2a="ring")
    assert two.n_hosts == 2 and two.devices_per_host == 4
    rng = np.random.default_rng(29)
    for step in range(2):
        reqs = mixed_corpus(rng, t, step, n=160)
        want = one.check(reqs, now_ms=t + step)
        got = two.check(reqs, now_ms=t + step)
        assert_resp_equal(want, got, f"hosts step={step}")
    np.testing.assert_array_equal(canon(one.snapshot()), canon(two.snapshot()))


def test_multihost_global_sync_convergence(frozen_now):
    """The hierarchical GLOBAL plane on a 2-host mesh: replica answers, the
    collective sync, and the converged authoritative state all match the
    1-D mesh — in-mesh reconcile is topology-invariant."""
    t = frozen_now
    one = GlobalShardedEngine(make_mesh(8), capacity_per_shard=2048,
                              sync_out=64, route="device", dedup="device",
                              a2a="collective")
    two = GlobalShardedEngine(make_mesh(8, hosts=2), capacity_per_shard=2048,
                              sync_out=64, route="device", dedup="device",
                              a2a="ring")
    rng = np.random.default_rng(31)
    for step in range(2):
        ks = rng.integers(0, 40, size=120)
        reqs = [
            req(
                f"g{k}",
                hits=1 + int(k) % 2,
                limit=500,
                behavior=(Behavior.GLOBAL if k % 2 else Behavior.BATCHING),
                created_at=t + step,
            )
            for k in ks
        ]
        cols = columns_from_requests(reqs)
        want = one.check_columns(cols, now_ms=t + step)
        got = two.check_columns(cols, now_ms=t + step)
        np.testing.assert_array_equal(want.status, got.status, f"step {step}")
        np.testing.assert_array_equal(want.remaining, got.remaining)
        np.testing.assert_array_equal(want.err, got.err)
    one.sync(now_ms=t + 2)
    two.sync(now_ms=t + 2)
    assert not one.has_pending() and not two.has_pending()
    np.testing.assert_array_equal(canon(one.snapshot()), canon(two.snapshot()))
    probe = columns_from_requests(
        [req(f"g{k}", hits=0, limit=500, behavior=Behavior.GLOBAL,
             created_at=t + 2) for k in range(0, 40, 2)]
    )
    want = one.check_columns(probe, now_ms=t + 2)
    got = two.check_columns(probe, now_ms=t + 2)
    np.testing.assert_array_equal(want.remaining, got.remaining)


# ------------------------------------------- inter-slice compact sync codec


def test_sync_wire_codec_roundtrip(frozen_now):
    """sync_wire_pb → sync_wire_items is exact for encodable batches, and
    the host lane decode agrees with the in-trace decode field-for-field."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.wire import sync_wire_items, sync_wire_pb

    t = frozen_now
    pairs = []
    for i in range(6):
        it = pb.RateLimitReq(
            name="glob", unique_key=f"k{i}", hits=(1 << 20) + i,
            limit=100 + i, duration=60_000, algorithm=i % 2,
            behavior=int(Behavior.GLOBAL)
            | (int(Behavior.RESET_REMAINING) if i == 3 else 0),
            created_at=t + i,
        )
        if it.algorithm == 1:
            it.burst = it.limit  # leaky default — encodable
        pairs.append((f"glob_k{i}", it))
    req_pb = sync_wire_pb(pairs, "src:1")
    assert req_pb is not None
    items = sync_wire_items(req_pb)
    for (_k, a), b in zip(pairs, items):
        assert (a.name, a.unique_key, a.hits, a.limit, a.duration,
                a.algorithm, a.created_at) == (
            b.name, b.unique_key, b.hits, b.limit, b.duration,
            b.algorithm, b.created_at,
        )
        assert b.behavior & int(Behavior.GLOBAL)
        assert (a.behavior & int(Behavior.RESET_REMAINING)) == (
            b.behavior & int(Behavior.RESET_REMAINING)
        )
    # host decode vs in-trace decode on one lane image
    from gubernator_tpu.ops.wire import WIRE_LANES, decode_wire_block, decode_wire_host

    n = len(pairs)
    lanes = np.frombuffer(req_pb.lanes, dtype="<i4").reshape(WIRE_LANES, n)
    host = decode_wire_host(lanes, int(req_pb.base))
    blk = np.zeros((WIRE_LANES, n + 1), dtype=np.int32)
    blk[:, :n] = lanes
    from gubernator_tpu.ops.wire import stamp_base

    stamp_base(blk, int(req_pb.base))
    arr12, base = jax.jit(decode_wire_block)(jnp.asarray(blk))
    arr12 = np.asarray(arr12)
    assert int(base) == int(req_pb.base)
    np.testing.assert_array_equal(arr12[0], host["fp"])
    np.testing.assert_array_equal(arr12[1], host["algo"])
    np.testing.assert_array_equal(arr12[2], host["behavior"])
    np.testing.assert_array_equal(arr12[4], host["limit"])
    np.testing.assert_array_equal(arr12[6], host["duration"])
    np.testing.assert_array_equal(arr12[7], host["created_at"])


def test_sync_wire_codec_fallbacks(frozen_now):
    """Every non-representable shape returns None (→ proto path), never a
    lossy encoding."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.wire import sync_wire_pb

    t = frozen_now

    def item(**kw):
        base = dict(name="g", unique_key="k", hits=1, limit=10,
                    duration=60_000, behavior=int(Behavior.GLOBAL),
                    created_at=t)
        base.update(kw)
        return pb.RateLimitReq(**base)

    ok = item()
    assert sync_wire_pb([("g_k", ok)], "s") is not None
    cases = {
        "multi_region": item(
            behavior=int(Behavior.GLOBAL | Behavior.MULTI_REGION)
        ),
        "gregorian": item(
            behavior=int(Behavior.GLOBAL | Behavior.DURATION_IS_GREGORIAN)
        ),
        "no_created_at": pb.RateLimitReq(
            name="g", unique_key="k", hits=1, limit=10, duration=60_000,
            behavior=int(Behavior.GLOBAL),
        ),
        "big_duration": item(duration=1 << 31),
        "big_limit": item(limit=1 << 33),
        "negative_limit": item(limit=-1),
        "token_burst": item(burst=5),
        "skew": None,  # below
    }
    for label, bad in cases.items():
        if bad is None:
            continue
        assert sync_wire_pb([("g_k", bad)], "s") is None, label
    # created_at skew beyond the ±511 ms delta budget of the batch base
    far = item(created_at=t + 5_000)
    assert sync_wire_pb([("g_k", ok), ("g_k2", far)], "s") is None
    # metadata (trace propagation) has no compact lane
    md = item()
    md.metadata["traceparent"] = "00-xyz"
    assert sync_wire_pb([("g_k", md)], "s") is None


def test_sync_globals_wire_over_grpc(frozen_now):
    """The compact inter-slice sync on the REAL peer plane: a non-owner
    accumulates ≥ _WIRE_MIN GLOBAL hits with created_at set, the sync round
    ships ONE SyncGlobalsWireReq, the owner applies + broadcasts, and every
    peer converges — with the wire/fallback split visible in /metrics."""
    from tests.cluster import Cluster, metric_value, scrape, wait_for

    async def run():
        c = await Cluster.start(3)
        from gubernator_tpu.client import V1Client

        clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
        try:
            owner = c.find_owning_daemon("glob", "wk0")
            # keys co-owned by one daemon so the batch groups onto one peer
            keys = [f"wk{i}" for i in range(60)
                    if c.find_owning_daemon("glob", f"wk{i}") is owner][:6]
            assert len(keys) >= 4, "need >= _WIRE_MIN co-owned keys"
            na = c.non_owning_daemons("glob", keys[0])[0]
            cl = clients[c.daemons.index(na)]
            t = frozen_now
            reqs = [
                RateLimitRequest(
                    name="glob", unique_key=k, hits=2, limit=100,
                    duration=60_000, behavior=Behavior.GLOBAL, created_at=t,
                )
                for k in keys
            ]
            resp = await cl.get_rate_limits(reqs)
            assert all(r.error == "" and r.remaining == 98
                       for r in resp.responses)

            async def wire_sent():
                s = await scrape(na)
                return metric_value(
                    s, "gubernator_global_wire_sync_entries_total",
                    direction="sent",
                )

            async def wire_recv():
                s = await scrape(owner)
                return metric_value(
                    s, "gubernator_global_wire_sync_entries_total",
                    direction="recv",
                )

            await wait_for(wire_sent, timeout_s=15)
            await wait_for(wire_recv, timeout_s=15)
            assert await wire_sent() == len(keys)
            assert await wire_recv() == len(keys)

            # convergence: the owner applied the synced hits and broadcast;
            # every daemon's local answer agrees
            async def converged():
                for d, dcl in zip(c.daemons, clients):
                    r = await dcl.get_rate_limits(
                        [RateLimitRequest(
                            name="glob", unique_key=keys[0], hits=0,
                            limit=100, duration=60_000,
                            behavior=Behavior.GLOBAL, created_at=t,
                        )]
                    )
                    if r.responses[0].remaining != 98:
                        return 0
                return 1

            await wait_for(converged, timeout_s=15)
        finally:
            for cl in clients:
                await cl.close()
            await c.stop()

    asyncio.run(run())


def test_sync_globals_wire_disabled_falls_back(frozen_now):
    """GUBER_GLOBAL_WIRE_SYNC=false (behaviors.global_wire_sync) keeps the
    classic proto path: convergence is identical and no wire entries are
    recorded — the parity oracle for the codec."""
    from gubernator_tpu.config import BehaviorConfig
    from tests.cluster import Cluster, metric_value, scrape, wait_for

    async def run():
        beh = BehaviorConfig(
            batch_wait_ms=1.0, global_sync_wait_ms=50.0,
            batch_timeout_ms=5000.0, global_timeout_ms=5000.0,
            global_wire_sync=False,
        )
        c = await Cluster.start(2, behaviors=beh)
        from gubernator_tpu.client import V1Client

        clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
        try:
            owner = c.find_owning_daemon("glob", "fb0")
            keys = [f"fb{i}" for i in range(60)
                    if c.find_owning_daemon("glob", f"fb{i}") is owner][:5]
            na = c.non_owning_daemons("glob", keys[0])[0]
            cl = clients[c.daemons.index(na)]
            t = frozen_now
            await cl.get_rate_limits([
                RateLimitRequest(
                    name="glob", unique_key=k, hits=1, limit=100,
                    duration=60_000, behavior=Behavior.GLOBAL, created_at=t,
                )
                for k in keys
            ])

            async def owner_applied():
                s = await scrape(owner)
                return metric_value(
                    s, "gubernator_broadcast_counter_total",
                    condition="broadcast",
                )

            await wait_for(owner_applied, timeout_s=15)
            s = await scrape(na)
            assert metric_value(
                s, "gubernator_global_wire_sync_entries_total",
                direction="sent",
            ) == 0
        finally:
            for cl in clients:
                await cl.close()
            await c.stop()

    asyncio.run(run())
