"""PendingHits unit tests — the columnar GLOBAL hit accumulator
(parallel/global_sync.py). The reference semantics it must reproduce are
the async-hit aggregation of global.go:109-123: sum Hits, OR
RESET_REMAINING, newest request's config wins; plus the take() pop used by
the sync outbox builder."""

import numpy as np

from gubernator_tpu.ops.batch import pack_requests
from gubernator_tpu.parallel.global_sync import PendingHits
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_700_000_000_000


def hb_for(specs):
    """specs: list of (key, hits, limit, behavior)."""
    reqs = [
        RateLimitRequest(
            name="p", unique_key=k, hits=h, limit=lim, duration=60_000,
            behavior=b, created_at=NOW,
        )
        for (k, h, lim, b) in specs
    ]
    hb, errs = pack_requests(reqs, NOW)
    assert all(e is None for e in errs)
    return hb


def test_merge_aggregates_within_batch():
    p = PendingHits()
    hb = hb_for([("a", 2, 10, 0), ("b", 1, 10, 0), ("a", 3, 99, 0)])
    p.merge(hb, np.arange(3), hb.hits.copy(),
            hb.behavior & np.int32(Behavior.RESET_REMAINING))
    assert len(p) == 2
    by_fp = dict(zip(p.hb.fp.tolist(), p.hits.tolist()))
    # same-key hits summed; newest config (limit=99) carried
    fa = hb.fp[0]
    assert by_fp[int(fa)] == 5
    carrier_limit = int(p.hb.limit[p.hb.fp.tolist().index(int(fa))])
    assert carrier_limit == 99


def test_merge_across_batches_sums_and_ors():
    p = PendingHits()
    hb1 = hb_for([("k", 1, 10, Behavior.RESET_REMAINING)])
    p.merge(hb1, np.array([0]), np.array([1], dtype=np.int64),
            hb1.behavior & np.int32(Behavior.RESET_REMAINING))
    hb2 = hb_for([("k", 4, 77, 0)])
    p.merge(hb2, np.array([0]), np.array([4], dtype=np.int64),
            hb2.behavior & np.int32(Behavior.RESET_REMAINING))
    assert len(p) == 1
    assert int(p.hits[0]) == 5
    assert int(p.reset[0]) == int(Behavior.RESET_REMAINING)  # OR survives
    assert int(p.hb.limit[0]) == 77  # newest config wins


def test_take_pops_disjoint_and_drains():
    p = PendingHits()
    hb = hb_for([(f"k{i}", 1, 10, 0) for i in range(10)])
    p.merge(hb, np.arange(10), hb.hits.copy(), np.zeros(10, dtype=np.int32))
    cfg1, hits1, _ = p.take(4)
    assert cfg1.fp.shape[0] == 4 and len(p) == 6
    cfg2, hits2, _ = p.take(100)  # over-ask drains the rest
    assert cfg2.fp.shape[0] == 6 and len(p) == 0
    assert p.hb is None
    # popped sets are disjoint and cover everything
    assert set(cfg1.fp.tolist()) | set(cfg2.fp.tolist()) == set(hb.fp.tolist())
    assert not set(cfg1.fp.tolist()) & set(cfg2.fp.tolist())


def test_take_views_do_not_alias_remainder():
    """Mutating a popped box (the outbox builder stamps hits/behavior/
    created_at in place) must never corrupt the entries still queued."""
    p = PendingHits()
    hb = hb_for([(f"k{i}", 1, 10, 0) for i in range(8)])
    p.merge(hb, np.arange(8), hb.hits.copy(), np.zeros(8, dtype=np.int32))
    cfg, hits, reset = p.take(4)
    remainder_before = p.hb.hits.copy()
    cfg.hits[:] = 999  # outbox-builder-style in-place stamp
    cfg.behavior[:] |= 0x7F
    np.testing.assert_array_equal(p.hb.hits, remainder_before)
    assert not (p.hb.behavior & 0x40).any()


def test_empty_accumulator():
    p = PendingHits()
    assert len(p) == 0
    # merging zero rows is a no-op that keeps the accumulator well-formed
    hb = hb_for([("x", 1, 10, 0)])
    p.merge(hb, np.arange(0), np.zeros(0, dtype=np.int64),
            np.zeros(0, dtype=np.int32))
    assert len(p) == 0


def test_clear_drops_everything():
    """clear() is the harness-reset entry point (bench.py's steady-state
    queue drain) — no more reaching into __slots__ private fields."""
    p = PendingHits()
    hb = hb_for([(f"k{i}", 1, 10, 0) for i in range(5)])
    p.merge(hb, np.arange(5), hb.hits.copy(), np.zeros(5, dtype=np.int32))
    assert len(p) == 5
    p.clear()
    assert len(p) == 0
    assert p.hb is None and p.hits is None and p.reset is None
    # cleared accumulator accepts fresh merges
    p.merge(hb, np.arange(5), hb.hits.copy(), np.zeros(5, dtype=np.int32))
    assert len(p) == 5


def test_take_popped_columns_are_copies():
    """The POPPED box must not share storage with the accumulator either
    (the de-alias guarantee take() now makes): stamping the popped columns
    in place — exactly what _build_box does — must never write through
    into entries still queued, in either drain order."""
    p = PendingHits()
    hb = hb_for([(f"k{i}", 1, 10, 0) for i in range(8)])
    p.merge(hb, np.arange(8), hb.hits.copy(), np.zeros(8, dtype=np.int32))
    cfg, hits, reset = p.take(4)
    assert not np.shares_memory(cfg.hits, p.hb.hits)
    assert not np.shares_memory(hits, p.hits)
    assert not np.shares_memory(reset, p.reset)
    # full-drain pop of the remainder is also a copy (accumulator nulls out)
    cfg2, hits2, _ = p.take(100)
    cfg2.hits[:] = 123  # must be dead storage now
    assert len(p) == 0


def test_owner_marker_zero_hits_entry_kept():
    """Owner-side rows queue with hits=0 (broadcast markers) and must
    survive aggregation as entries — the sync round broadcasts them even
    though they contribute no hits."""
    p = PendingHits()
    hb = hb_for([("own", 3, 10, 0)])
    p.merge(hb, np.array([0]), np.array([0], dtype=np.int64),
            np.zeros(1, dtype=np.int32))
    assert len(p) == 1
    assert int(p.hits[0]) == 0
