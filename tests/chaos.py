"""Fault-injection harness — an in-process chaos TCP proxy.

Each test-cluster daemon can be fronted by one ChaosProxy: peers dial the
proxy's port (the daemon advertises it), the proxy pipes bytes to the real
gRPC listener, and tests toggle failure modes per-peer at runtime — so the
fault-tolerance layer (service/breaker.py, degraded-local fallback, GLOBAL
requeue) is exercised against *real* failing RPCs, not mocks.

Modes
-----
* "pass"      — transparent byte pipe (default)
* "delay"     — transparent, but each chunk is delayed by `delay_s`
* "drop"      — new connections are accepted and immediately closed
                (connection-refused-like fast failures)
* "error"     — connections establish, then reset on the first client bytes
                (mid-stream RPC failures)
* "blackhole" — connections establish but nothing is ever forwarded or
                answered (the slow timeout failures breakers exist for)

Switching modes severs existing connections, so a long-lived HTTP/2 channel
can't tunnel through a freshly injected fault — nor stay wedged on a
blackholed socket after a heal.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Optional, Set

MODES = ("pass", "delay", "drop", "error", "blackhole")


class ChaosProxy:
    def __init__(self):
        self.mode = "pass"
        self.delay_s = 0.0
        self.port: Optional[int] = None
        self.target_host: Optional[str] = None
        self.target_port: Optional[int] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: Set[asyncio.Task] = set()
        self._writers: Set[asyncio.StreamWriter] = set()
        self._holes: Set[asyncio.Event] = set()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    async def start(self) -> "ChaosProxy":
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def set_target(self, host: str, port: int) -> None:
        self.target_host, self.target_port = host, port

    def set_mode(self, mode: str, delay_s: float = 0.0) -> None:
        """Switch the failure mode at runtime. Every switch severs live
        connections so the new mode applies immediately: a gRPC channel
        would otherwise keep its established HTTP/2 stream through a fresh
        fault — or, on heal, stay wedged on a blackholed socket."""
        assert mode in MODES, f"unknown chaos mode {mode!r}"
        self.mode = mode
        self.delay_s = delay_s
        self.sever()

    def heal(self) -> None:
        self.set_mode("pass")

    def sever(self) -> None:
        """Kill every live connection (blackholed ones included)."""
        for ev in list(self._holes):
            ev.set()
        for w in list(self._writers):
            with contextlib.suppress(Exception):
                w.transport.abort()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.sever()
        for t in list(self._conns):
            t.cancel()
        await asyncio.gather(*self._conns, return_exceptions=True)

    # ------------------------------------------------------------- internals
    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        self._writers.add(writer)
        try:
            await self._serve_conn(reader, writer)
        except (asyncio.CancelledError, ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            self._conns.discard(task)
            with contextlib.suppress(Exception):
                writer.close()

    async def _serve_conn(self, reader, writer) -> None:
        mode = self.mode  # the mode at accept time governs this connection
        if mode == "drop":
            writer.transport.abort()
            return
        if mode == "blackhole":
            # swallow inbound bytes, answer nothing, hold the socket open
            # until severed/healed — the caller is left waiting on its RPC
            # deadline, exactly like a dead host behind a silent LB
            hole = asyncio.Event()
            self._holes.add(hole)
            drain = asyncio.create_task(self._drain_forever(reader))
            try:
                await hole.wait()
            finally:
                self._holes.discard(hole)
                drain.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await drain
            writer.transport.abort()
            return
        if mode == "error":
            # let the connection establish, reset on first client bytes
            with contextlib.suppress(Exception):
                await reader.read(1)
            writer.transport.abort()
            return
        # pass / delay: full duplex pipe to the real listener
        assert self.target_port is not None, "chaos proxy has no target"
        try:
            up_r, up_w = await asyncio.open_connection(
                self.target_host, self.target_port
            )
        except OSError:
            writer.transport.abort()
            return
        self._writers.add(up_w)
        try:
            await asyncio.gather(
                self._pipe(reader, up_w),
                self._pipe(up_r, writer),
            )
        finally:
            self._writers.discard(up_w)
            with contextlib.suppress(Exception):
                up_w.close()

    async def _drain_forever(self, reader) -> None:
        with contextlib.suppress(Exception):
            while await reader.read(65536):
                pass

    async def _pipe(self, reader, writer) -> None:
        try:
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    break
                if self.mode == "delay" and self.delay_s > 0:
                    await asyncio.sleep(self.delay_s)
                writer.write(chunk)
                await writer.drain()
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            with contextlib.suppress(Exception):
                writer.write_eof()
