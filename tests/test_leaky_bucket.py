"""Leaky-bucket semantic tests (reference TestLeakyBucket functional_test.go:478,
negative hits :783, more-than-available :854, gregorian :712)."""

import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
    MINUTE,
    SECOND,
)


def req(key="lk1", hits=1, limit=5, duration=5 * SECOND, burst=0, behavior=0, created_at=None):
    return RateLimitRequest(
        name="test",
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=duration,
        algorithm=Algorithm.LEAKY_BUCKET,
        behavior=behavior,
        burst=burst,
        created_at=created_at,
    )


@pytest.fixture
def eng():
    return LocalEngine(capacity=1024)


def test_drain_and_leak_refill(eng, frozen_now):
    # limit 5 per 5s → one token per second
    t = frozen_now
    for i in range(5):
        (r,) = eng.check([req(created_at=t)], now_ms=t)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 4 - i
    (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT

    # after one rate interval a whole token has leaked back
    t2 = t + 1000
    (r,) = eng.check([req(created_at=t2)], now_ms=t2)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0  # the leaked token was immediately consumed

    # sub-token elapsed time yields nothing (reference algorithms.go:363:
    # `if int64(leak) > 0`)
    t3 = t2 + 999
    (r,) = eng.check([req(created_at=t3)], now_ms=t3)
    assert r.status == Status.OVER_LIMIT


def test_full_refill_caps_at_burst(eng, frozen_now):
    t = frozen_now
    for _ in range(5):
        eng.check([req(created_at=t)], now_ms=t)
    t2 = t + 60 * SECOND  # far more than needed to refill 5
    (r,) = eng.check([req(hits=0, created_at=t2)], now_ms=t2)
    assert r.remaining == 5  # clamped to burst (= limit)


def test_burst_overrides_capacity(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=8, limit=5, burst=10, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 2


def test_over_ask_does_not_consume(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    (r,) = eng.check([req(hits=4, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 3
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0


def test_drain_over_limit(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    (r,) = eng.check(
        [req(hits=4, behavior=Behavior.DRAIN_OVER_LIMIT, created_at=t)], now_ms=t
    )
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    (r,) = eng.check([req(hits=1, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT


def test_first_request_over_burst(eng, frozen_now):
    # new leaky item with hits > burst starts drained (reference
    # algorithms.go:467-476)
    t = frozen_now
    (r,) = eng.check([req(hits=7, limit=5, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    (r,) = eng.check([req(hits=1, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT


def test_negative_hits(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.remaining == 2
    (r,) = eng.check([req(hits=-2, created_at=t)], now_ms=t)
    assert r.remaining == 4


def test_reset_remaining_refills(eng, frozen_now):
    # leaky RESET_REMAINING refills to burst in place (reference
    # algorithms.go:319-321) — unlike token bucket it does not remove the item
    t = frozen_now
    for _ in range(5):
        eng.check([req(created_at=t)], now_ms=t)
    (r,) = eng.check(
        [req(hits=1, behavior=Behavior.RESET_REMAINING, created_at=t)], now_ms=t
    )
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 4


def test_reset_time_tracks_deficit(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    # rate = 5000/5 = 1000 ms per token; 2 consumed → reset in 2 rate units
    assert r.reset_time == t + 2 * 1000


def test_exact_remainder(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0
    assert r.reset_time == t + 5 * 1000


def test_zero_hits_probe(eng, frozen_now):
    t = frozen_now
    eng.check([req(hits=2, created_at=t)], now_ms=t)
    (r,) = eng.check([req(hits=0, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 3
