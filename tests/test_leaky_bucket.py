"""Leaky-bucket semantic tests (reference TestLeakyBucket functional_test.go:478,
negative hits :783, more-than-available :854, gregorian :712)."""

import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
    MINUTE,
    SECOND,
)


def req(key="lk1", hits=1, limit=5, duration=5 * SECOND, burst=0, behavior=0, created_at=None):
    return RateLimitRequest(
        name="test",
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=duration,
        algorithm=Algorithm.LEAKY_BUCKET,
        behavior=behavior,
        burst=burst,
        created_at=created_at,
    )


@pytest.fixture
def eng():
    return LocalEngine(capacity=1024)


def test_drain_and_leak_refill(eng, frozen_now):
    # limit 5 per 5s → one token per second
    t = frozen_now
    for i in range(5):
        (r,) = eng.check([req(created_at=t)], now_ms=t)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 4 - i
    (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT

    # after one rate interval a whole token has leaked back
    t2 = t + 1000
    (r,) = eng.check([req(created_at=t2)], now_ms=t2)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0  # the leaked token was immediately consumed

    # sub-token elapsed time yields nothing (reference algorithms.go:363:
    # `if int64(leak) > 0`)
    t3 = t2 + 999
    (r,) = eng.check([req(created_at=t3)], now_ms=t3)
    assert r.status == Status.OVER_LIMIT


def test_full_refill_caps_at_burst(eng, frozen_now):
    t = frozen_now
    for _ in range(5):
        eng.check([req(created_at=t)], now_ms=t)
    t2 = t + 60 * SECOND  # far more than needed to refill 5
    (r,) = eng.check([req(hits=0, created_at=t2)], now_ms=t2)
    assert r.remaining == 5  # clamped to burst (= limit)


def test_burst_overrides_capacity(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=8, limit=5, burst=10, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 2


def test_over_ask_does_not_consume(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    (r,) = eng.check([req(hits=4, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 3
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0


def test_drain_over_limit(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    (r,) = eng.check(
        [req(hits=4, behavior=Behavior.DRAIN_OVER_LIMIT, created_at=t)], now_ms=t
    )
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    (r,) = eng.check([req(hits=1, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT


def test_first_request_over_burst(eng, frozen_now):
    # new leaky item with hits > burst starts drained (reference
    # algorithms.go:467-476)
    t = frozen_now
    (r,) = eng.check([req(hits=7, limit=5, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    (r,) = eng.check([req(hits=1, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT


def test_negative_hits(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.remaining == 2
    (r,) = eng.check([req(hits=-2, created_at=t)], now_ms=t)
    assert r.remaining == 4


def test_reset_remaining_refills(eng, frozen_now):
    # leaky RESET_REMAINING refills to burst in place (reference
    # algorithms.go:319-321) — unlike token bucket it does not remove the item
    t = frozen_now
    for _ in range(5):
        eng.check([req(created_at=t)], now_ms=t)
    (r,) = eng.check(
        [req(hits=1, behavior=Behavior.RESET_REMAINING, created_at=t)], now_ms=t
    )
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 4


def test_reset_time_tracks_deficit(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    # rate = 5000/5 = 1000 ms per token; 2 consumed → reset in 2 rate units
    assert r.reset_time == t + 2 * 1000


def test_exact_remainder(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    (r,) = eng.check([req(hits=3, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0
    assert r.reset_time == t + 5 * 1000


def test_zero_hits_probe(eng, frozen_now):
    t = frozen_now
    eng.check([req(hits=2, created_at=t)], now_ms=t)
    (r,) = eng.check([req(hits=0, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 3


# ----------------------------------------------- remainder precision bounds


def test_sub_millisecond_rate_div_regression(eng, frozen_now):
    """rate = duration/limit < 1 ms/token must not divide away the deficit
    (reference TestLeakyBucketDivBug regression, functional_test.go:1569:
    duration 1000 ms, limit 2000 → rate 0.5 ms/token)."""
    t = frozen_now
    r = eng.check(
        [req(key="div", hits=1, limit=2000, duration=1000, created_at=t)],
        now_ms=t,
    )[0]
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 1999, 2000)
    r = eng.check(
        [req(key="div", hits=100, limit=2000, duration=1000, created_at=t)],
        now_ms=t,
    )[0]
    assert (r.remaining, r.limit) == (1899, 2000)


def test_leaky_out_of_range_limit_and_burst_rejected(eng, frozen_now):
    """Limits/bursts beyond int32 are REJECTED at validation (pack_columns
    ERR_LIMIT_I32/ERR_BURST_I32) — the guard that keeps every storable leaky
    remainder inside the double-single f32 domain. The reference accepts
    int64 limits (store.go:31); divergence documented in ops/kernel2.py."""
    for bad in (2**40, 2**47, 2**50):
        (r,) = eng.check([req(limit=bad)], now_ms=frozen_now)
        assert "32" in r.error and r.status == Status.UNDER_LIMIT
        (r,) = eng.check([req(limit=5, burst=bad)], now_ms=frozen_now)
        assert "32" in r.error


def test_leaky_remainder_survives_roundtrips_at_i32_extremes(eng, frozen_now):
    """Store/load roundtrips of the double-single f32 remainder stay exact
    against a float64 oracle at the largest representable configs: integer
    remainders are bit-exact, fractional refills within 2^-17 tokens (the
    48-bit mantissa bound measured in ops/kernel2.py's divergence note)."""
    limit = 2**31 - 1  # max accepted
    dur = MINUTE
    t = frozen_now
    # drain in uneven chunks across dispatches → many store/load roundtrips
    oracle = float(limit)
    hits_seq = [1, 2**30, 3, 2**29 + 7, 11, 2**28 + 1]
    for h in hits_seq:
        (r,) = eng.check([req(key="big", hits=h, limit=limit, duration=dur,
                              created_at=t)], now_ms=t)
        oracle -= h
        assert r.error == ""
        assert r.remaining == int(oracle)  # integer domain: bit-exact
    # fractional refill: advance by a prime ms count; rate = dur/limit ms/token
    rate = dur / limit
    adv = 104729  # ms
    t2 = t + adv
    (r,) = eng.check([req(key="big", hits=0, limit=limit, duration=dur,
                          created_at=t2)], now_ms=t2)
    oracle = min(float(limit), oracle + adv / rate)
    # truncation boundary: allow 1 token of slack for the 2^-17 resolution
    assert abs(r.remaining - int(oracle)) <= 1
    # and further roundtrips must not drift: repeat zero-hit reads
    for _ in range(5):
        (r2,) = eng.check([req(key="big", hits=0, limit=limit, duration=dur,
                               created_at=t2)], now_ms=t2)
        assert r2.remaining == r.remaining
