"""Native ingress/egress (gubernator_tpu/native) parity tests: wire parsing,
hashing, and response encoding must match the pure-Python pb path exactly."""

import asyncio
import functools
import random

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.proto import gubernator_pb2 as pb

m = native.load()
pytestmark = pytest.mark.skipif(m is None, reason="native toolchain unavailable")


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def random_req(rng, i):
    r = pb.RateLimitReq(
        name=rng.choice(["svc", "üñïçødé-svc", "a" * 40, "x"]),
        unique_key=f"key-{i}-{rng.randrange(1000)}",
        hits=rng.choice([0, 1, 5, -3, 1 << 40]),
        limit=rng.choice([0, 10, 1 << 31, -7]),
        duration=rng.choice([1000, 60_000, 3]),  # 3 = a Gregorian enum value
        algorithm=rng.choice([0, 1]),
        behavior=rng.choice([0, 1, 2, 8, 32, 34]),
        burst=rng.choice([0, 5]),
    )
    if rng.random() < 0.5:
        r.created_at = rng.randrange(1, 1 << 45)
    if rng.random() < 0.3:
        r.metadata["traceparent"] = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        r.metadata["other"] = "värde"
    return r


def test_parse_matches_pb_path():
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.peers.hash_ring import fnv1a_32
    from gubernator_tpu.service.wire import columns_from_pb, columns_from_wire

    rng = random.Random(7)
    items = [random_req(rng, i) for i in range(200)]
    items.append(pb.RateLimitReq(name="no-key"))  # ERR_EMPTY_KEY
    items.append(pb.RateLimitReq(unique_key="no-name"))  # ERR_EMPTY_NAME
    data = pb.GetRateLimitsReq(requests=items).SerializeToString()

    got = columns_from_wire(data)
    assert got is not None
    cols, ring, spans, traceparent = got
    # at least one random item carried the traceparent metadata
    assert traceparent == "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    ref_cols, hash_keys = columns_from_pb(items)

    for field in ("fp", "algo", "behavior", "hits", "burst", "created_at", "err"):
        np.testing.assert_array_equal(
            getattr(cols, field), getattr(ref_cols, field), err_msg=field
        )
    # limit/duration are clipped by columns_from_pb only beyond ±2^62 —
    # unclipped here, so compare raw
    np.testing.assert_array_equal(cols.limit, [it.limit for it in items])
    np.testing.assert_array_equal(cols.duration, [it.duration for it in items])
    # ring points match the python ring hash of the hash key
    for i, hk in enumerate(hash_keys):
        if hk:
            assert int(ring[i]) == fnv1a_32(hk.encode()), hk
    # spans re-materialize the exact item
    from gubernator_tpu.service.wire import item_from_span

    for i in (0, 57, 199):
        assert item_from_span(data, spans[i]) == items[i]


def test_encode_matches_pb():
    from gubernator_tpu.service.wire import encode_response_columns

    n = 50
    rng = np.random.default_rng(3)
    status = rng.integers(0, 2, n).astype(np.int64)
    limit = rng.integers(0, 1 << 40, n)
    remaining = rng.integers(0, 1 << 40, n)
    reset = rng.integers(0, 1 << 45, n)
    errors = {0: "boom", 17: "fält-fel: üñï"}
    data = encode_response_columns(status, limit, remaining, reset, errors)
    resp = pb.GetRateLimitsResp.FromString(data)
    assert len(resp.responses) == n
    for i, r in enumerate(resp.responses):
        assert r.status == status[i]
        assert r.limit == limit[i]
        assert r.remaining == remaining[i]
        assert r.reset_time == reset[i]
        assert r.error == errors.get(i, "")


def test_malformed_wire_raises():
    with pytest.raises(ValueError):
        m.parse_get_rate_limits(b"\x0a\xff\xff\xff\xff\xff")  # truncated len


@async_test
async def test_raw_path_serves_cluster_traffic():
    """The raw gRPC path end-to-end on a 3-daemon cluster: local, forwarded,
    and GLOBAL items all answered from the native ingress."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.types import Behavior

    from tests.cluster import Cluster, wait_for

    c = await Cluster.start(3)
    try:
        non_owner = c.non_owning_daemons("nat", "k1")[0]
        owner = c.find_owning_daemon("nat", "k1")
        client = V1Client(non_owner.conf.grpc_address)
        try:
            resp = await client.get_rate_limits(
                [
                    dict(name="nat", unique_key="k1", hits=2, limit=10, duration=60_000),
                    dict(name="nat", unique_key="k2", hits=1, limit=10, duration=60_000),
                    dict(name="", unique_key="bad", hits=1, limit=1, duration=1000),
                    dict(
                        name="nat", unique_key="g1", hits=3, limit=10,
                        duration=60_000, behavior=int(Behavior.GLOBAL),
                    ),
                ]
            )
            r = resp.responses
            assert r[0].error == "" and r[0].remaining == 8
            assert r[1].error == "" and r[1].remaining == 9
            assert "namespace" in r[2].error
            assert r[3].error == "" and r[3].remaining == 7

            # the GLOBAL hit reaches the owner asynchronously
            async def owner_saw_hits():
                ro = await owner.get_rate_limits(
                    [pb.RateLimitReq(name="nat", unique_key="g1", hits=0,
                                     limit=10, duration=60_000)]
                )
                return ro[0].remaining == 7

            await wait_for(owner_saw_hits, timeout_s=15)
        finally:
            await client.close()
    finally:
        await c.stop()


@async_test
async def test_raw_path_force_global():
    """GUBER_FORCE_GLOBAL on the native raw path: requests flip to GLOBAL,
    serve locally, and the owner broadcast still fires (the forced bit must
    survive lazy pb materialization)."""
    from gubernator_tpu.client import V1Client

    from tests.cluster import Cluster, daemon_config, metric_value, scrape, wait_for

    from gubernator_tpu.config import BehaviorConfig

    behaviors = BehaviorConfig(
        batch_wait_ms=1.0, global_sync_wait_ms=50.0,
        batch_timeout_ms=5000.0, global_timeout_ms=5000.0, force_global=True,
    )
    c = await Cluster.start(2, behaviors=behaviors)
    try:
        owner = c.find_owning_daemon("fg", "k1")
        client = V1Client(owner.conf.grpc_address)
        try:
            resp = await client.get_rate_limits(
                [dict(name="fg", unique_key="k1", hits=2, limit=10, duration=60_000)]
            )
            assert resp.responses[0].error == ""
            assert resp.responses[0].remaining == 8
        finally:
            await client.close()

        # forced-GLOBAL owner hits must broadcast to the peer
        async def broadcasted():
            s = await scrape(owner)
            return metric_value(
                s, "gubernator_broadcast_counter_total", condition="broadcast"
            )

        await wait_for(broadcasted, timeout_s=15)
        other = c.non_owning_daemons("fg", "k1")[0]

        async def installed():
            s = await scrape(other)
            return metric_value(
                s, "gubernator_update_peer_globals_installed_total"
            )

        await wait_for(installed, timeout_s=15)
    finally:
        await c.stop()
