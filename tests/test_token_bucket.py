"""Token-bucket semantic tests.

Scenario tables modeled on the reference's black-box functional suite
(reference functional_test.go: TestTokenBucket:161, TestDrainOverLimit:369,
more-than-available:435, TestChangeLimit:1344, TestResetRemaining:1439,
negative hits:297) — behavior parity, not code parity.
"""

import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
    MINUTE,
    SECOND,
)


def req(key="k1", hits=1, limit=5, duration=MINUTE, behavior=0, created_at=None, name="test"):
    return RateLimitRequest(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=duration,
        algorithm=Algorithm.TOKEN_BUCKET,
        behavior=behavior,
        created_at=created_at,
    )


@pytest.fixture
def eng():
    return LocalEngine(capacity=1024)


def test_basic_decrement_and_over_limit(eng, frozen_now):
    t = frozen_now
    for i in range(5):
        (r,) = eng.check([req(created_at=t)], now_ms=t)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 4 - i
        assert r.limit == 5
        assert r.reset_time == t + MINUTE
    (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0


def test_expiry_renews_bucket(eng, frozen_now):
    t = frozen_now
    for _ in range(6):
        (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    t2 = t + MINUTE + 1  # ExpireAt < now → expired (reference cache.go:50-52)
    (r,) = eng.check([req(created_at=t2)], now_ms=t2)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 4
    assert r.reset_time == t2 + MINUTE


def test_zero_hits_reports_without_consuming(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(hits=2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    (r,) = eng.check([req(hits=0, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 3
    (r,) = eng.check([req(hits=0, created_at=t)], now_ms=t)
    assert r.remaining == 3


def test_over_ask_does_not_consume(eng, frozen_now):
    # reference semantics note algorithms.go:29-34 and functional_test.go:435
    t = frozen_now
    (r,) = eng.check([req(hits=20, limit=100, created_at=t)], now_ms=t)
    assert r.remaining == 80
    (r,) = eng.check([req(hits=81, limit=100, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 80
    (r,) = eng.check([req(hits=80, limit=100, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0


def test_first_request_over_limit(eng, frozen_now):
    # new item with hits > limit answers OVER but keeps a full bucket
    # (reference algorithms.go:236-243)
    t = frozen_now
    (r,) = eng.check([req(hits=10, limit=5, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 5
    (r,) = eng.check([req(hits=5, limit=5, created_at=t)], now_ms=t)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0


def test_drain_over_limit(eng, frozen_now):
    # reference TestDrainOverLimit functional_test.go:369
    t = frozen_now
    (r,) = eng.check([req(hits=2, limit=10, created_at=t)], now_ms=t)
    assert r.remaining == 8
    (r,) = eng.check(
        [req(hits=9, limit=10, behavior=Behavior.DRAIN_OVER_LIMIT, created_at=t)],
        now_ms=t,
    )
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    (r,) = eng.check([req(hits=1, limit=10, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT


def test_negative_hits_adds_back(eng, frozen_now):
    # reference functional_test.go:297 — negative hits return tokens
    t = frozen_now
    (r,) = eng.check([req(hits=4, created_at=t)], now_ms=t)
    assert r.remaining == 1
    (r,) = eng.check([req(hits=-2, created_at=t)], now_ms=t)
    assert r.remaining == 3
    # and can exceed the limit (no top clamp, matching the reference)
    (r,) = eng.check([req(hits=-10, created_at=t)], now_ms=t)
    assert r.remaining == 13


def test_reset_remaining(eng, frozen_now):
    # reference TestResetRemaining functional_test.go:1439
    t = frozen_now
    for _ in range(5):
        (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.remaining == 0
    (r,) = eng.check(
        [req(hits=0, behavior=Behavior.RESET_REMAINING, created_at=t)], now_ms=t
    )
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 5
    assert r.reset_time == 0
    (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.remaining == 4


def test_change_limit_midflight(eng, frozen_now):
    # reference TestChangeLimit functional_test.go:1344 — delta applied to
    # remaining, clamped at zero (algorithms.go:108-115)
    t = frozen_now
    (r,) = eng.check([req(hits=5, limit=10, created_at=t)], now_ms=t)
    assert r.remaining == 5
    (r,) = eng.check([req(hits=1, limit=20, created_at=t)], now_ms=t)
    assert r.remaining == 14  # 5 + (20-10) - 1
    (r,) = eng.check([req(hits=1, limit=5, created_at=t)], now_ms=t)
    # 15 + (5-20) = -10 → clamped to 0 → at limit
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0


def test_change_duration_extends_expiry(eng, frozen_now):
    t = frozen_now
    (r,) = eng.check([req(created_at=t)], now_ms=t)
    assert r.reset_time == t + MINUTE
    t2 = t + 10 * SECOND
    (r,) = eng.check([req(duration=2 * MINUTE, created_at=t2)], now_ms=t2)
    # new expiry anchored at the item's CreatedAt (reference algorithms.go:126)
    assert r.reset_time == t + 2 * MINUTE
    assert r.remaining == 3


def test_change_duration_into_the_past_renews(eng, frozen_now):
    # if CreatedAt + new duration is already past, the bucket renews
    # (reference algorithms.go:134-141)
    t = frozen_now
    eng.check([req(hits=3, duration=MINUTE, created_at=t)], now_ms=t)
    t2 = t + 10 * SECOND
    (r,) = eng.check([req(hits=1, duration=5 * SECOND, created_at=t2)], now_ms=t2)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 4  # renewed to full, then one hit
    assert r.reset_time == t2 + 5 * SECOND


def test_sticky_over_status_on_status_read(eng, frozen_now):
    # hitting the floor persists OVER into the item; a hits=0 probe then
    # reports the stored status (reference algorithms.go:117-122,161-167)
    t = frozen_now
    eng.check([req(hits=5, created_at=t)], now_ms=t)
    (r,) = eng.check([req(hits=1, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    (r,) = eng.check([req(hits=0, created_at=t)], now_ms=t)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0


def test_algorithm_switch_recreates(eng, frozen_now):
    t = frozen_now
    eng.check([req(hits=3, created_at=t)], now_ms=t)
    leaky = RateLimitRequest(
        name="test",
        unique_key="k1",
        hits=1,
        limit=5,
        duration=MINUTE,
        algorithm=Algorithm.LEAKY_BUCKET,
        created_at=t,
    )
    (r,) = eng.check([leaky], now_ms=t)
    # recreated as a fresh leaky bucket (reference algorithms.go:307-317)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 4
    back = req(hits=1, created_at=t)
    (r,) = eng.check([back], now_ms=t)
    assert r.remaining == 4  # fresh token bucket again


def test_batch_of_distinct_keys(eng, frozen_now):
    t = frozen_now
    rs = [req(key=f"k{i}", hits=1, limit=3, created_at=t) for i in range(50)]
    out = eng.check(rs, now_ms=t)
    assert all(r.status == Status.UNDER_LIMIT and r.remaining == 2 for r in out)


def test_same_key_sequential_within_batch(eng, frozen_now):
    # duplicate keys in one batch apply sequentially via planner passes
    t = frozen_now
    rs = [req(hits=2, limit=5, created_at=t), req(hits=2, limit=5, created_at=t),
          req(hits=2, limit=5, created_at=t)]
    out = eng.check(rs, now_ms=t)
    assert [r.remaining for r in out] == [3, 1, 1]
    assert [r.status for r in out] == [
        Status.UNDER_LIMIT,
        Status.UNDER_LIMIT,
        Status.OVER_LIMIT,  # 2 > 1 remaining → rejected, not consumed
    ]
