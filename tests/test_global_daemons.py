"""Cross-daemon GLOBAL behavior — convergence asserted by scraping /metrics.

The reference's signature distributed test technique (TestGlobalBehavior,
functional_test.go:1760-2167): drive GLOBAL hits at specific daemons, poll
each daemon's REAL /metrics endpoint for exact broadcast/update counts, then
verify every peer converged to the same remaining.

This covers the HOST peer plane (service/global_manager.py over gRPC); the
in-mesh collective plane has its own suite (tests/test_global.py).
"""

import asyncio
import functools

from gubernator_tpu.client import V1Client
from gubernator_tpu.types import Behavior, RateLimitRequest

from tests.cluster import Cluster, metric_value, scrape, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def greq(key, name="glob", hits=1, limit=100):
    return RateLimitRequest(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=60_000,
        behavior=Behavior.GLOBAL,
    )


async def broadcast_count(daemon) -> float:
    s = await scrape(daemon)
    return metric_value(
        s, "gubernator_broadcast_counter_total", condition="broadcast"
    )


async def updates_installed(daemon) -> float:
    s = await scrape(daemon)
    return metric_value(s, "gubernator_update_peer_globals_installed_total")


@async_test
async def test_global_hits_converge_via_owner_broadcast():
    """Non-owner takes GLOBAL hits → async-sends to owner → owner broadcasts →
    every peer's local answer converges (TestGlobalRateLimits analog,
    functional_test.go:961)."""
    c = await Cluster.start(3)
    clients = {d.conf.advertise_address: V1Client(d.conf.grpc_address) for d in c.daemons}
    try:
        owner = c.find_owning_daemon("glob", "gk1")
        non_owners = c.non_owning_daemons("glob", "gk1")
        na = non_owners[0]
        # 5 hits at a NON-owner: answered locally, queued async
        resp = await clients[na.conf.advertise_address].get_rate_limits(
            [greq("gk1", hits=5)]
        )
        (r,) = resp.responses
        assert r.error == ""
        assert r.remaining == 95  # local replica answered immediately

        # owner applies the async hits and broadcasts exactly once
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)
        # every non-owner installed the authoritative status
        for d in non_owners:
            await wait_for(lambda d=d: updates_installed(d), timeout_s=15)

        # EXACT counter accounting, scraped over the wire — asserted BEFORE
        # the convergence reads below: a zero-hit GLOBAL read at the owner
        # queues ANOTHER broadcast (owner-path QueueUpdate fires for every
        # GLOBAL request, reference gubernator.go:670-672), which would bump
        # these counters on the next sync tick
        assert await broadcast_count(owner) == 2.0  # one per non-owner peer
        for d in non_owners:
            assert await broadcast_count(d) == 0.0
            assert await updates_installed(d) == 1.0

        # all daemons now agree (each answers locally with hits=0)
        for d in c.daemons:
            resp = await clients[d.conf.advertise_address].get_rate_limits(
                [greq("gk1", hits=0)]
            )
            assert resp.responses[0].remaining == 95, d.conf.advertise_address
    finally:
        for cl in clients.values():
            await cl.close()
        await c.stop()


@async_test
async def test_global_owner_hit_broadcasts():
    """Hits AT the owner also queue a broadcast (QueueUpdate on the owner
    path, gubernator.go:670-672)."""
    c = await Cluster.start(3)
    owner = c.find_owning_daemon("glob", "gk2")
    client = V1Client(owner.conf.grpc_address)
    try:
        resp = await client.get_rate_limits([greq("gk2", hits=3)])
        assert resp.responses[0].remaining == 97
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)
        assert await broadcast_count(owner) == 2.0
        for d in c.non_owning_daemons("glob", "gk2"):
            await wait_for(lambda d=d: updates_installed(d), timeout_s=15)
            # non-owner answers from its replica without contacting the owner
            cl = V1Client(d.conf.grpc_address)
            r = (await cl.get_rate_limits([greq("gk2", hits=0)])).responses[0]
            await cl.close()
            assert r.remaining == 97
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_global_aggregates_hits_across_non_owners():
    """Hits from MULTIPLE non-owners aggregate on the owner; remaining
    reflects the sum after one sync round (TestGlobalBehavior's
    multi-updater case)."""
    c = await Cluster.start(3)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        owner = c.find_owning_daemon("glob", "gk3")
        owner_idx = c.daemons.index(owner)
        total = 0
        for i, d in enumerate(c.daemons):
            if i == owner_idx:
                continue
            await clients[i].get_rate_limits([greq("gk3", hits=4)])
            total += 4
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)

        async def converged():
            r = (
                await clients[owner_idx].get_rate_limits([greq("gk3", hits=0)])
            ).responses[0]
            return r.remaining == 100 - total

        await wait_for(converged, timeout_s=15)
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()
