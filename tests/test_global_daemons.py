"""Cross-daemon GLOBAL behavior — convergence asserted by scraping /metrics.

The reference's signature distributed test technique (TestGlobalBehavior,
functional_test.go:1760-2167): drive GLOBAL hits at specific daemons, poll
each daemon's REAL /metrics endpoint for exact broadcast/update counts, then
verify every peer converged to the same remaining.

This covers the HOST peer plane (service/global_manager.py over gRPC); the
in-mesh collective plane has its own suite (tests/test_global.py).
"""

import asyncio
import functools

from gubernator_tpu.client import V1Client
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

from tests.cluster import Cluster, metric_value, scrape, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def greq(key, name="glob", hits=1, limit=100):
    return RateLimitRequest(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=60_000,
        behavior=Behavior.GLOBAL,
    )


async def broadcast_count(daemon) -> float:
    s = await scrape(daemon)
    return metric_value(
        s, "gubernator_broadcast_counter_total", condition="broadcast"
    )


async def updates_installed(daemon) -> float:
    s = await scrape(daemon)
    return metric_value(s, "gubernator_update_peer_globals_installed_total")


@async_test
async def test_global_hits_converge_via_owner_broadcast():
    """Non-owner takes GLOBAL hits → async-sends to owner → owner broadcasts →
    every peer's local answer converges (TestGlobalRateLimits analog,
    functional_test.go:961)."""
    c = await Cluster.start(3)
    clients = {d.conf.advertise_address: V1Client(d.conf.grpc_address) for d in c.daemons}
    try:
        owner = c.find_owning_daemon("glob", "gk1")
        non_owners = c.non_owning_daemons("glob", "gk1")
        na = non_owners[0]
        # 5 hits at a NON-owner: answered locally, queued async
        resp = await clients[na.conf.advertise_address].get_rate_limits(
            [greq("gk1", hits=5)]
        )
        (r,) = resp.responses
        assert r.error == ""
        assert r.remaining == 95  # local replica answered immediately

        # owner applies the async hits and broadcasts exactly once
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)
        # every non-owner installed the authoritative status
        for d in non_owners:
            await wait_for(lambda d=d: updates_installed(d), timeout_s=15)

        # EXACT counter accounting, scraped over the wire — asserted BEFORE
        # the convergence reads below: a zero-hit GLOBAL read at the owner
        # queues ANOTHER broadcast (owner-path QueueUpdate fires for every
        # GLOBAL request, reference gubernator.go:670-672), which would bump
        # these counters on the next sync tick
        assert await broadcast_count(owner) == 2.0  # one per non-owner peer
        for d in non_owners:
            assert await broadcast_count(d) == 0.0
            assert await updates_installed(d) == 1.0

        # all daemons now agree (each answers locally with hits=0)
        for d in c.daemons:
            resp = await clients[d.conf.advertise_address].get_rate_limits(
                [greq("gk1", hits=0)]
            )
            assert resp.responses[0].remaining == 95, d.conf.advertise_address
    finally:
        for cl in clients.values():
            await cl.close()
        await c.stop()


@async_test
async def test_global_owner_hit_broadcasts():
    """Hits AT the owner also queue a broadcast (QueueUpdate on the owner
    path, gubernator.go:670-672)."""
    c = await Cluster.start(3)
    owner = c.find_owning_daemon("glob", "gk2")
    client = V1Client(owner.conf.grpc_address)
    try:
        resp = await client.get_rate_limits([greq("gk2", hits=3)])
        assert resp.responses[0].remaining == 97
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)
        assert await broadcast_count(owner) == 2.0
        for d in c.non_owning_daemons("glob", "gk2"):
            await wait_for(lambda d=d: updates_installed(d), timeout_s=15)
            # non-owner answers from its replica without contacting the owner
            cl = V1Client(d.conf.grpc_address)
            r = (await cl.get_rate_limits([greq("gk2", hits=0)])).responses[0]
            await cl.close()
            assert r.remaining == 97
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_global_negative_hits_propagate():
    """Negative GLOBAL hits RAISE remaining beyond the limit and propagate
    through owner broadcasts so later peers see the credit
    (TestGlobalNegativeHits, functional_test.go)."""
    c = await Cluster.start(4)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        peers = c.non_owning_daemons("glob", "neg")
        pc = [clients[c.daemons.index(d)] for d in peers]

        async def send(cl, hits, want_remaining):
            r = (
                await cl.get_rate_limits(
                    [greq("neg", hits=hits, limit=2)]
                )
            ).responses[0]
            assert r.error == ""
            assert r.remaining == want_remaining, (hits, r.remaining)

        async def installed_at_least(d, k):
            return (await updates_installed(d)) >= k

        # fresh bucket at peer0's replica: limit 2 minus (-1) = 3
        await send(pc[0], -1, 3)
        await wait_for(lambda: installed_at_least(peers[1], 1), timeout_s=20)
        # peer1's replica saw the broadcast (remaining 3); another credit → 4
        await send(pc[1], -1, 4)
        await wait_for(lambda: installed_at_least(peers[2], 2), timeout_s=20)
        # peer2 consumes all 4 banked tokens in one request
        await send(pc[2], 4, 0)
        await wait_for(lambda: installed_at_least(peers[0], 3), timeout_s=20)
        await send(pc[0], 0, 0)
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()


@async_test
async def test_global_request_more_than_available():
    """Peers spread GLOBAL hits that together exceed the limit: each answers
    UNDER from its replica (the documented over-consumption window), and
    after the owner aggregates + broadcasts, further hits are OVER
    (TestGlobalRequestMoreThanAvailable, functional_test.go)."""
    c = await Cluster.start(3)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        peers = c.non_owning_daemons("glob", "over")
        pc = [clients[c.daemons.index(d)] for d in peers]

        def lreq(hits):
            return RateLimitRequest(
                name="glob", unique_key="over", hits=hits, limit=100,
                duration=600_000, behavior=Behavior.GLOBAL,
                algorithm=Algorithm.LEAKY_BUCKET,
            )

        # 50 hits at each non-owner: both UNDER locally (replicas are
        # independent until the sync round lands)
        for cl in pc:
            r = (await cl.get_rate_limits([lreq(50)])).responses[0]
            assert r.error == ""
            assert r.status == 0

        # the owner must aggregate BOTH peers' 50s and broadcast remaining 0
        # — probe with ZERO hits so the wait cannot satisfy itself by
        # consuming the local replica (each replica alone still holds 50)
        async def depleted():
            r = (await pc[0].get_rate_limits([lreq(0)])).responses[0]
            return r.remaining == 0

        await wait_for(depleted, timeout_s=20)
        r = (await pc[0].get_rate_limits([lreq(1)])).responses[0]
        assert r.status == 1
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()


@async_test
async def test_global_load_balanced_owner_and_non_owner():
    """Alternating GLOBAL hits between the owner and a non-owner (the
    round-robin-LB client pattern) deplete one shared limit and then both
    report OVER (TestGlobalRateLimitsWithLoadBalancing, functional_test.go)."""
    c = await Cluster.start(3)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        owner = c.find_owning_daemon("glob", "lb")
        non_owner = c.non_owning_daemons("glob", "lb")[0]
        oc = clients[c.daemons.index(owner)]
        nc = clients[c.daemons.index(non_owner)]

        r = (await oc.get_rate_limits([greq("lb", hits=1, limit=2)])).responses[0]
        assert (r.error, r.status) == ("", 0)
        r = (await nc.get_rate_limits([greq("lb", hits=1, limit=2)])).responses[0]
        assert (r.error, r.status) == ("", 0)

        # pin the SYNC, not local depletion: zero-hit reads at BOTH ends
        # must converge to the aggregated remaining (0) — the non-owner's
        # replica alone would still hold 1 if broadcasts were broken
        async def synced_to_zero():
            a = (await oc.get_rate_limits([greq("lb", hits=0, limit=2)])).responses[0]
            b = (await nc.get_rate_limits([greq("lb", hits=0, limit=2)])).responses[0]
            return a.remaining == 0 and b.remaining == 0

        await wait_for(synced_to_zero, timeout_s=20)
        # every further hit is OVER at either end, and stays OVER
        for cl in (oc, nc, nc):
            r = (await cl.get_rate_limits([greq("lb", hits=1, limit=2)])).responses[0]
            assert r.status == 1
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()


@async_test
async def test_global_aggregates_hits_across_non_owners():
    """Hits from MULTIPLE non-owners aggregate on the owner; remaining
    reflects the sum after one sync round (TestGlobalBehavior's
    multi-updater case)."""
    c = await Cluster.start(3)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        owner = c.find_owning_daemon("glob", "gk3")
        owner_idx = c.daemons.index(owner)
        total = 0
        for i, d in enumerate(c.daemons):
            if i == owner_idx:
                continue
            await clients[i].get_rate_limits([greq("gk3", hits=4)])
            total += 4
        await wait_for(lambda: broadcast_count(owner), timeout_s=15)

        async def converged():
            r = (
                await clients[owner_idx].get_rate_limits([greq("gk3", hits=0)])
            ).responses[0]
            return r.remaining == 100 - total

        await wait_for(converged, timeout_s=15)
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()


@async_test
async def test_sliding_window_broadcast_carries_prev_window_aux():
    """PR-11 GLOBAL fidelity regression: owner broadcasts of SLIDING_WINDOW
    keys must carry the previous-window count (and stored-style remaining)
    so a replica interpolates the SAME `used` as the owner. Before the fix
    the install rebuilt windows with prev=0, so a replica right after a
    window roll answered far more permissively than the owner."""
    import time

    c = await Cluster.start(2, created_at_tolerance_ms=3_600_000.0)
    clients = {
        d.conf.advertise_address: V1Client(d.conf.grpc_address)
        for d in c.daemons
    }
    try:
        name, key = "wing", "wk1"
        owner = c.find_owning_daemon(name, key)
        replica = c.non_owning_daemons(name, key)[0]
        ocl = clients[owner.conf.advertise_address]
        rcl = clients[replica.conf.advertise_address]
        dur, limit = 600_000, 100
        now = time.time_ns() // 1_000_000
        ws = now - now % dur
        t_prev = ws - dur // 2  # middle of the PREVIOUS window
        t_cur = ws + max(1, (now - ws) // 2)  # inside the current window

        def wreq(hits, created):
            from gubernator_tpu.types import Algorithm

            return RateLimitRequest(
                name=name, unique_key=key, hits=hits, limit=limit,
                duration=dur, algorithm=Algorithm.SLIDING_WINDOW,
                behavior=Behavior.GLOBAL, created_at=created,
            )

        # 40 hits land in window W-1 at the owner, then 10 in window W —
        # the owner's state is (cur=10, prev=40)
        r = (await ocl.get_rate_limits([wreq(40, t_prev)])).responses[0]
        assert r.error == "" and r.status == 0
        r = (await ocl.get_rate_limits([wreq(10, t_cur)])).responses[0]
        assert r.error == "" and r.status == 0

        t_q = t_cur + 10
        own = (await ocl.get_rate_limits([wreq(0, t_q)])).responses[0]
        weighted_prev = (40 * (dur - (t_q - ws))) // dur
        assert weighted_prev > 0  # the regression needs a live prev weight
        assert own.remaining == limit - 10 - weighted_prev

        # the replica converges to the owner's EXACT interpolated answer
        async def replica_matches():
            rep = (await rcl.get_rate_limits([wreq(0, t_q)])).responses[0]
            return rep.remaining == own.remaining

        await wait_for(replica_matches, timeout_s=15)
    finally:
        for cl in clients.values():
            await cl.close()
        await c.stop()
