"""Hot-set tiering oracle suite (gubernator_tpu/tier/, docs/tiering.md).

Pins the ISSUE 15 acceptance surface: the evictee sidecar (XLA and Pallas
kernels, both wire formats), demote/promote roundtrip BIT-exactness per
slot layout through the canonical-row conversion point, under-grant-only
under duplicated/stale promotes, Zipf churn against a bounded shadow with
zero over-grant, the shadow byte bound + LRU shed accounting, spill-file
fault-back, 8-device mesh demote/fault-back parity, and the checkpoint
interplay (demote → kill -9 → restart → fault-back from shadow, not
resurrection from a stale delta frame).
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import jax.numpy as jnp
import pytest

from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.ops.kernel2 import decide2_packed_cols, unpack_evictees
from gubernator_tpu.ops.table2 import Table2, extract_idle_rows, new_table2
from gubernator_tpu.tier import ROW_BYTES, ShadowTable

NOW = 1_700_000_000_000
HOUR = 3_600_000


def cols(fp, now, hits=1, limit=10, algo=0, duration=HOUR, burst=0):
    n = fp.shape[0]
    mk = lambda v, dt: np.full(n, v, dtype=dt)
    return RequestColumns(
        fp=np.asarray(fp, dtype=np.int64),
        algo=mk(algo, np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=mk(hits, np.int64),
        limit=mk(limit, np.int64),
        burst=mk(burst, np.int64),
        duration=mk(duration, np.int64),
        created_at=mk(now, np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def arr12(fp, now, hits=1, limit=10):
    n = fp.shape[0]
    z = np.zeros(n, dtype=np.int64)
    mk = lambda v: np.full(n, v, dtype=np.int64)
    return jnp.asarray(np.stack([
        np.asarray(fp, dtype=np.int64), z, z, mk(hits), mk(limit), z,
        mk(HOUR), mk(now), mk(now + HOUR), z, mk(HOUR),
        (np.asarray(fp) != 0).astype(np.int64),
    ]))


def shadowed_engine(capacity=256, max_bytes=1 << 22, spill=None, **kw):
    eng = LocalEngine(capacity=capacity, write_mode="xla", **kw)
    eng.attach_shadow(ShadowTable(max_bytes=max_bytes, spill_path=spill))
    return eng


# ------------------------------------------------------------ sidecar


def test_evictee_sidecar_captures_victim_rows():
    """A full bucket's displaced live rows ride the dispatch outputs:
    fingerprints and full pre-dispatch state, count == the kernel's
    evicted_unexpired stat."""
    t = new_table2(8)  # ONE bucket of 8 slots
    seed = np.arange(1, 9, dtype=np.int64)
    t, _ = decide2_packed_cols(
        t, arr12(seed, NOW, hits=3), write="xla", math="token"
    )
    newk = np.arange(101, 105, dtype=np.int64)
    pad = np.zeros(16, dtype=np.int64)
    pad[:4] = newk
    hits = np.zeros(16, dtype=np.int64)
    hits[:4] = 1
    t, out = decide2_packed_cols(
        t, arr12(pad, NOW + 5, hits=1).at[3].set(jnp.asarray(hits)),
        write="xla", math="token", evictees=True,
    )
    host = np.asarray(out)
    from gubernator_tpu.ops.kernel2 import unpack_outputs

    _, st = unpack_outputs(host, 4)
    fps, rows = unpack_evictees(host)
    assert st[3] == fps.shape[0] == 4
    assert set(fps.tolist()) <= set(seed.tolist())
    # victim state is the PRE-dispatch row: limit 10, 3 consumed
    assert (rows[:, 2] == 10).all() and (rows[:, 4] == 7).all()


def test_evictee_sidecar_absent_without_flag():
    """evictees=False keeps the historic (B+2, 4) output shape — the
    zero-cost contract for tiering-off deployments."""
    t = new_table2(8)
    t, out = decide2_packed_cols(
        t, arr12(np.arange(1, 17, dtype=np.int64), NOW), write="xla",
        math="token",
    )
    assert np.asarray(out).shape == (18, 4)


def test_evictee_sidecar_parity_xla_vs_pallas():
    """The Pallas megakernel's sidecar (deferred-inserter patches and all)
    is bit-identical to the XLA path's — outputs AND table bytes."""
    rng = np.random.default_rng(11)
    t0 = new_table2(64)
    seed = rng.integers(1, 1 << 60, size=64, dtype=np.int64)
    t0, _ = decide2_packed_cols(
        t0, arr12(seed, NOW), write="xla", math="token"
    )
    rows_np = np.asarray(t0.rows)
    batch = arr12(rng.integers(1, 1 << 60, size=32, dtype=np.int64), NOW + 5)
    tx = Table2(rows=jnp.asarray(rows_np.copy()))
    tp = Table2(rows=jnp.asarray(rows_np.copy()))
    tx, ox = decide2_packed_cols(
        tx, batch, write="xla", math="token", evictees=True
    )
    tp, op = decide2_packed_cols(
        tp, batch, write="xla", math="token", evictees=True, probe="pallas"
    )
    assert np.array_equal(np.asarray(ox), np.asarray(op))
    assert np.array_equal(np.asarray(tx.rows), np.asarray(tp.rows))
    fx, rx = unpack_evictees(np.asarray(ox))
    assert fx.shape[0] > 0  # the scenario actually evicts


def test_evictee_sidecar_rides_compact_wire():
    """The engine's compact-wire dispatches carry the sidecar too: an
    evicting dispatch through a wire='compact' engine lands the victim
    rows in the shadow."""
    eng = shadowed_engine(capacity=8, wire="compact")
    seed = np.arange(1, 9, dtype=np.int64)
    eng.check_columns(cols(seed, NOW, hits=3), now_ms=NOW)
    eng.check_columns(
        cols(np.arange(101, 109, dtype=np.int64), NOW + 5), now_ms=NOW + 5
    )
    st = eng.shadow.stats()
    assert st["demoted_evict"] > 0
    assert eng.stats.evicted_unexpired >= st["demoted_evict"] > 0


# --------------------------------------------- roundtrip exactness


@pytest.mark.parametrize("layout,algo", [
    ("full", 0), ("gcra32", 2), ("token32", 0),
])
def test_demote_promote_roundtrip_bit_exact(layout, algo):
    """An unexpired row demoted (idle sweep) and faulted back re-packs to
    the SAME table bytes in every registered slot layout — the
    canonical-row conversion point is lossless for rows the layout can
    hold."""
    eng = LocalEngine(capacity=64, write_mode="xla", layout=layout)
    fp = np.array([12345], dtype=np.int64)
    eng.check_columns(cols(fp, NOW, hits=3, algo=algo), now_ms=NOW)
    found, before = eng.read_state(fp, raw=True)
    assert found[0]
    # demote: extract idle + tombstone (idle horizon 0 → everything idle)
    fps, slots = eng.extract_idle(NOW + 1000, 1)
    assert fp[0] in fps.tolist()
    eng.tombstone_fps(fps)
    found, _ = eng.read_state(fp)
    assert not found[0]
    full = np.asarray(eng.table.layout.unpack(slots))
    sh = ShadowTable(max_bytes=1 << 20)
    sh.offer(fps, full, NOW + 1000, reason="idle")
    # promote through the conservative merge
    pf, rows = sh.take(fp, NOW + 1000)
    assert pf.shape[0] == 1
    from gubernator_tpu.ops.layout import FULL

    eng.merge_rows(pf, rows, now_ms=NOW + 1000, layout=FULL)
    found, after = eng.read_state(fp, raw=True)
    assert found[0]
    i = list(fps).index(fp[0])
    np.testing.assert_array_equal(after[0], before[0])
    # and the shadow row itself equals the canonical unpack of the bytes
    np.testing.assert_array_equal(
        rows[0], np.asarray(eng.table.layout.unpack(before))[0]
    )


def test_stale_duplicate_promote_under_grants_only():
    """A stale or duplicated promote can only tighten: re-offering an OLD
    copy of a row and promoting it over newer state never raises
    remaining above the newer state's."""
    eng = shadowed_engine(capacity=64)
    fp = np.array([777], dtype=np.int64)
    eng.check_columns(cols(fp, NOW, hits=2), now_ms=NOW)  # rem 8
    _, old_row = eng.read_state(fp)  # canonical full row, rem 8
    eng.check_columns(cols(fp, NOW + 10, hits=5), now_ms=NOW + 10)  # rem 3
    # stale re-offer + forced promote
    eng.shadow.offer(fp, old_row, NOW + 20)
    rc = eng.check_columns(cols(fp, NOW + 30, hits=0), now_ms=NOW + 30)
    assert rc.remaining[0] <= 3  # min-merge: stale promote can't re-grant
    # duplicated promote of the same bytes is idempotent
    eng.shadow.offer(fp, old_row, NOW + 40)
    rc = eng.check_columns(cols(fp, NOW + 50, hits=0), now_ms=NOW + 50)
    assert rc.remaining[0] <= 3


# ------------------------------------------------- zero over-grant


def _drive(eng, keys, passes=4, wave=128, hits=3, limit=10):
    adm = {int(k): 0 for k in keys}
    t = NOW
    for _ in range(passes):
        for i in range(0, len(keys), wave):
            w = keys[i:i + wave]
            rc = eng.check_columns(
                cols(w, t, hits=hits, limit=limit), now_ms=t
            )
            ok = (rc.status == 0) & (rc.err == 0)
            for j in np.nonzero(ok)[0]:
                adm[int(w[j])] += hits
            t += 7
    return adm


def test_tiering_zero_over_grant_at_4x_tracked_keys():
    """The acceptance core: 4× tracked keys beyond table capacity, every
    key's total admissions ≤ its limit — eviction became a tiering event
    instead of a permissive re-grant. The identical drive WITHOUT tiering
    over-grants (the bug being fixed)."""
    rng = np.random.default_rng(3)
    CAP, TRACKED, LIMIT = 256, 1024, 10
    keys = np.unique(
        rng.integers(1, 1 << 62, size=TRACKED + 64, dtype=np.int64)
    )[:TRACKED]
    eng = shadowed_engine(capacity=CAP)
    adm = _drive(eng, keys, limit=LIMIT)
    over = [k for k, v in adm.items() if v > LIMIT]
    assert not over, f"{len(over)} keys over-granted with tiering on"
    assert eng.shadow.stats()["demoted_evict"] > 0  # tiering actually ran

    ctrl = LocalEngine(capacity=CAP, write_mode="xla")
    adm2 = _drive(ctrl, keys, limit=LIMIT)
    assert any(v > LIMIT for v in adm2.values()), (
        "control run did not over-grant; the scenario no longer "
        "exercises eviction"
    )


def test_zipf_churn_bounded_shadow_no_over_grant():
    """Zipf-shaped churn over 4× tracked keys against a shadow big enough
    to hold the cold set: hot keys stay exact, the byte bound holds."""
    rng = np.random.default_rng(17)
    CAP, TRACKED, LIMIT = 256, 1024, 50
    keys = np.unique(
        rng.integers(1, 1 << 62, size=TRACKED + 64, dtype=np.int64)
    )[:TRACKED]
    eng = shadowed_engine(capacity=CAP, max_bytes=TRACKED * ROW_BYTES)
    adm = {int(k): 0 for k in keys}
    # zipf ranks: heavy head, long tail
    zipf = np.minimum(rng.zipf(1.3, size=24 * 128) - 1, TRACKED - 1)
    t = NOW
    for i in range(24):
        w = keys[zipf[i * 128:(i + 1) * 128]]
        w = np.unique(w)  # unique-fp per batch (the serving contract)
        rc = eng.check_columns(cols(w, t, hits=1, limit=LIMIT), now_ms=t)
        ok = (rc.status == 0) & (rc.err == 0)
        for j in np.nonzero(ok)[0]:
            adm[int(w[j])] += 1
        t += 11
    assert all(v <= LIMIT for v in adm.values())
    st = eng.shadow.stats()
    assert st["nominal_bytes"] <= TRACKED * ROW_BYTES


# --------------------------------------------------- byte bound / spill


def test_shadow_byte_bound_and_lru_shed():
    sh = ShadowTable(max_bytes=4 * ROW_BYTES)
    fps = np.arange(1, 11, dtype=np.int64)
    rows = np.zeros((10, 16), dtype=np.int32)
    rows[:, 0] = fps.astype(np.int32)
    rows[:, 10] = 1  # exp_lo > 0 → live vs now=0
    sh.offer(fps, rows, 0)
    assert sh.nominal_bytes <= sh.max_bytes
    assert sh.ram_rows == 4
    assert sh.shed == 6  # oldest-first, counted
    # the 4 newest survive
    f, _ = sh.take(fps, 0)
    assert set(f.tolist()) == {7, 8, 9, 10}


def test_shadow_spill_overflow_and_faultback(tmp_path):
    """Over-budget rows shed to the spill file losslessly and fault back
    with one seek+read; a fresh ShadowTable re-indexes the file."""
    path = str(tmp_path / "spill")
    sh = ShadowTable(max_bytes=4 * ROW_BYTES, spill_path=path)
    fps = np.arange(1, 11, dtype=np.int64)
    rows = np.zeros((10, 16), dtype=np.int32)
    rows[:, 0] = fps.astype(np.int32)
    rows[:, 4] = fps.astype(np.int32)  # distinguishable payload
    rows[:, 10] = 1
    sh.offer(fps, rows, 0)
    assert sh.shed == 0
    f, r = sh.take(np.array([2], dtype=np.int64), 0)  # spilled row
    assert list(f) == [2] and r[0, 4] == 2
    sh.flush(0)
    sh2 = ShadowTable(max_bytes=1 << 20, spill_path=path)
    assert sh2.load() > 0
    f, r = sh2.take(np.array([9], dtype=np.int64), 0)
    assert list(f) == [9] and r[0, 4] == 9


def test_shadow_conflict_merges_conservatively():
    """Two demotes of one fingerprint keep the tighter remaining and the
    later expiry (merge2's rules, host-side)."""
    sh = ShadowTable(max_bytes=1 << 20)
    fp = np.array([5], dtype=np.int64)
    a = np.zeros((1, 16), dtype=np.int32)
    a[0, 0] = 5
    a[0, 4] = 8
    a[0, 10] = 100
    b = a.copy()
    b[0, 4] = 3
    b[0, 10] = 200
    sh.offer(fp, a, 0)
    sh.offer(fp, b, 0)
    assert sh.conflicts_merged == 1
    f, r = sh.take(fp, 0)
    assert r[0, 4] == 3 and r[0, 10] == 200


# ------------------------------------------------------ idle sweep


def test_extract_idle_respects_horizon_and_cap():
    eng = LocalEngine(capacity=256, write_mode="xla")
    old = np.arange(1, 33, dtype=np.int64)
    new = np.arange(101, 133, dtype=np.int64)
    eng.check_columns(cols(old, NOW), now_ms=NOW)
    eng.check_columns(cols(new, NOW + 50_000), now_ms=NOW + 50_000)
    fps, _ = eng.extract_idle(NOW + 60_000, 30_000)
    assert set(fps.tolist()) == set(old.tolist())
    capped, _ = eng.extract_idle(NOW + 60_000, 30_000, max_rows=5)
    assert capped.shape[0] == 5


def test_idle_demote_then_faultback_preserves_state():
    """The full demote-on-idle → fault-back loop at the engine level:
    state leaves HBM, the next check for the key resumes EXACTLY where it
    left off."""
    eng = shadowed_engine(capacity=256)
    fp = np.array([4242], dtype=np.int64)
    eng.check_columns(cols(fp, NOW, hits=6), now_ms=NOW)  # rem 4
    fps, slots = eng.extract_idle(NOW + 60_000, 30_000)
    eng.tombstone_fps(fps)
    full = np.asarray(eng.table.layout.unpack(slots))
    eng.shadow.offer(fps, full, NOW + 60_000, reason="idle")
    found, _ = eng.read_state(fp)
    assert not found[0]
    rc = eng.check_columns(
        cols(fp, NOW + 61_000, hits=1), now_ms=NOW + 61_000
    )
    assert rc.remaining[0] == 3  # 10 - 6 - 1: no re-grant
    assert eng.shadow.stats()["promoted"] == fps.shape[0]


# ------------------------------------------------------ pipelined path


def test_pipelined_check_promotes_and_demotes():
    """The prepare/issue/finish pipeline (EngineRunner.check) probes the
    shadow at prepare, merges at issue, and harvests the sidecar at
    finish — same zero-re-grant outcome as the serial path."""
    from gubernator_tpu.service.runner import EngineRunner

    rng = np.random.default_rng(23)
    keys = np.unique(rng.integers(1, 1 << 62, size=1100,
                                  dtype=np.int64))[:1024]
    eng = shadowed_engine(capacity=256)
    runner = EngineRunner(eng)

    async def drive():
        t = NOW
        # pass 1: every key consumes 6 of 10 (4x tracked keys → demotes)
        for i in range(0, 1024, 128):
            await runner.check(cols(keys[i:i + 128], t, hits=6), now_ms=t)
            t += 7
        # pass 2: +6 must deny for EVERY key (no fresh re-grant)
        denied = 0
        for i in range(0, 1024, 128):
            rc = await runner.check(
                cols(keys[i:i + 128], t, hits=6), now_ms=t
            )
            denied += int(((rc.status == 1) & (rc.err == 0)).sum())
            t += 7
        return denied

    try:
        denied = asyncio.run(drive())
        assert denied == 1024, f"only {denied}/1024 denied"
        assert eng.shadow.stats()["demoted_evict"] > 0
    finally:
        runner.close()


# ---------------------------------------------------------- 8-dev mesh


def test_sharded_idle_demote_faultback_8dev():
    """ShardedEngine tiering surface: per-shard extract-idle, tombstone,
    shadow fault-back through the routed merge — state preserved exactly
    across the demote/promote cycle on the 8-device mesh."""
    from gubernator_tpu.parallel import ShardedEngine, make_mesh

    mesh = make_mesh(8)
    eng = ShardedEngine(mesh, capacity_per_shard=64, write_mode="xla")
    eng.attach_shadow = lambda s: setattr(eng, "shadow", s)  # plain attr
    eng.shadow = ShadowTable(max_bytes=1 << 20)
    keys = np.unique(
        np.random.default_rng(5).integers(1, 1 << 60, size=64,
                                          dtype=np.int64)
    )
    eng.check_columns(cols(keys, NOW, hits=4), now_ms=NOW)
    fps, slots = eng.extract_idle(NOW + 60_000, 30_000)
    assert set(fps.tolist()) == set(keys.tolist())
    eng.tombstone_fps(fps)
    full = np.asarray(eng.table.layout.unpack(slots))
    eng.shadow.offer(fps, full, NOW + 60_000, reason="idle")
    found, _ = eng.read_state(keys)
    assert not found.any()
    rc = eng.check_columns(
        cols(keys, NOW + 61_000, hits=1), now_ms=NOW + 61_000
    )
    assert (np.asarray(rc.remaining) == 5).all()  # 10 - 4 - 1, preserved
    # collect=True surface: promote evictions come back typed
    n, mask, ev_f, ev_r = eng.merge_rows(
        fps[:4], full[:4], now_ms=NOW + 62_000, collect=True
    )
    assert mask.shape == (4,) and ev_r.shape[1] == 16


# ---------------------------------------------------- durability interplay


def test_tombstone_frame_roundtrip(tmp_path):
    from gubernator_tpu.store import (
        TOMBSTONE,
        DeltaLog,
        fps_from_slots,
    )

    log = DeltaLog(str(tmp_path / "d.delta"))
    rows = np.zeros((2, 16), dtype=np.int32)
    rows[:, 0] = [1, 2]
    log.append(4, 1000, rows)
    log.append_tombstones(5, 2000, np.array([2, (1 << 40) + 7],
                                            dtype=np.int64))
    scan = log.scan()
    assert scan.error is None
    assert scan.frames[1][3] is TOMBSTONE
    assert fps_from_slots(scan.frames[1][2]).tolist() == [2, (1 << 40) + 7]


@pytest.mark.slow
def test_demote_kill9_restart_faults_back_from_shadow(tmp_path):
    """The regression the ISSUE names: demote → kill -9 → restart — the
    key must NOT resurrect from a stale delta frame (the tombstone frame
    wins) and must fault back from the shadow spill with its consumption
    intact."""
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from tests.cluster import Cluster

    async def run():
        c = await Cluster.start(
            1, cache_size=256,
            checkpoint_path=str(tmp_path / "ckpt.bin"),
            checkpoint_interval_ms=40.0,
            tier_enabled=True,
            tier_idle_ms=100.0,
            tier_shadow_bytes=1 << 22,
            tier_spill_path=str(tmp_path / "spill"),
            # long cadence → only the EXPLICIT sweep below runs, so the
            # tombstone frame is durably appended before the kill
            telemetry_interval_ms=60_000.0,
        )
        d = c.daemons[0]
        fp = fingerprint("t", "k")
        try:
            r = (await d.get_rate_limits([pb.RateLimitReq(
                name="t", unique_key="k", hits=7, limit=10,
                duration=600_000,
            )]))[0]
            assert r.status == pb.UNDER_LIMIT and r.remaining == 3
            # one checkpoint epoch captures the write (the stale frame a
            # resurrect would replay), then the row idles past 100 ms
            await asyncio.sleep(0.3)
            await d.tier.sweep_once()
            assert d.tier.shadow.stats()["demoted_idle"] >= 1
            found, _ = d.engine.read_state(np.array([fp], dtype=np.int64))
            assert not found[0]
            d2 = await c.crash_restart(0)
            found, _ = d2.engine.read_state(np.array([fp], dtype=np.int64))
            assert not found[0], "resurrected from a stale delta frame"
            r = (await d2.get_rate_limits([pb.RateLimitReq(
                name="t", unique_key="k", hits=1, limit=10,
                duration=600_000,
            )]))[0]
            assert r.remaining == 2, (
                f"fault-back lost state: remaining {r.remaining}, "
                "expected 2 (7 consumed pre-crash + 1)"
            )
        finally:
            await c.stop()

    asyncio.run(run())


# ---------------------------------------------------------- config/debug


def test_tier_config_validation():
    from gubernator_tpu.config import ConfigError, setup_daemon_config

    with pytest.raises(ConfigError):
        setup_daemon_config(env={"GUBER_TIER_IDLE_MS": "0"})
    with pytest.raises(ConfigError):
        setup_daemon_config(env={"GUBER_TIER_SHADOW_BYTES": "8"})
    with pytest.raises(ConfigError):
        setup_daemon_config(env={
            "GUBER_TIER_ENABLED": "true",
            "GUBER_TIER_SPILL_PATH": "/nonexistent-dir-xyz/spill",
        })
    conf = setup_daemon_config(env={
        "GUBER_TIER_ENABLED": "true",
        "GUBER_TIER_IDLE_MS": "30s",
        "GUBER_TIER_SHADOW_BYTES": str(1 << 20),
    })
    assert conf.tier_enabled and conf.tier_idle_ms == 30_000.0


def test_debug_tier_and_metrics(tmp_path):
    """Daemon wiring: /v1/debug/tier schema, the evicted_live_total field
    on /v1/debug/table, and the gubernator_tpu_evicted_live_total +
    gubernator_tier_* families on /metrics."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.metrics import parse_metrics
    from tests.cluster import Cluster

    async def run():
        c = await Cluster.start(
            1, cache_size=64,  # small: force evictions across waves
            tier_enabled=True,
            tier_idle_ms=60_000.0,
            tier_shadow_bytes=1 << 20,
            telemetry_interval_ms=60_000.0,
        )
        d = c.daemons[0]
        try:
            for w in range(8):
                reqs = [
                    pb.RateLimitReq(name="t", unique_key=f"k{w}.{i}",
                                    hits=2, limit=10, duration=600_000)
                    for i in range(32)
                ]
                for r in (await d.get_rate_limits(reqs)):
                    assert not r.error
            dbg = d.debug_tier()
            assert dbg["enabled"] and dbg["shadow"]["demoted_evict"] > 0
            tbl = await d.debug_table()
            assert tbl["evicted_live_total"] > 0
            assert "tiering" in tbl
            d.tier.observe()
            fams = parse_metrics(d.metrics.render().decode())
            assert fams["gubernator_tpu_evicted_live_total"][()] > 0
            demo = fams["gubernator_tier_demoted_rows_total"]
            assert demo[(("reason", "evict"),)] > 0
            assert "gubernator_tier_shadow_rows" in fams
        finally:
            await c.stop()

    asyncio.run(run())
