"""Multi-device sharding tests on the virtual 8-device CPU mesh.

The analog of the reference's in-process cluster suite (cluster/cluster.go
boots N daemons; functional_test.go drives owner and non-owner nodes): here the
"cluster" is the device mesh, ownership is fingerprint→shard routing, and one
shard_map dispatch serves all shards at once.
"""

import numpy as np
import pytest

import jax

from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.mesh import shard_of
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status, MINUTE


def req(key, hits=1, limit=10, duration=MINUTE, algorithm=Algorithm.TOKEN_BUCKET,
        created_at=None):
    return RateLimitRequest(
        name="sh", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=algorithm, created_at=created_at,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


def test_all_shards_receive_and_persist(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    keys = [f"k{i}" for i in range(256)]
    out = eng.check([req(k, created_at=t) for k in keys], now_ms=t)
    assert all(r.status == Status.UNDER_LIMIT and r.remaining == 9 for r in out)
    # all shards actually hold keys (fingerprints spread over 8 shards)
    from gubernator_tpu.ops.batch import pack_requests
    hb, _ = pack_requests([req(k, created_at=t) for k in keys], t)
    shards = shard_of(hb.fp, 8)
    assert len(set(shards.tolist())) == 8
    # second round decrements every key on its shard
    out = eng.check([req(k, created_at=t) for k in keys], now_ms=t)
    assert all(r.remaining == 8 for r in out)


def test_sequential_semantics_across_shards(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    # duplicate keys + distinct keys mixed in one call
    rs = [req("dup", hits=4, limit=10, created_at=t),
          req("other", hits=1, limit=5, created_at=t),
          req("dup", hits=4, limit=10, created_at=t),
          req("dup", hits=4, limit=10, created_at=t)]
    out = eng.check(rs, now_ms=t)
    assert [r.remaining for r in out] == [6, 4, 2, 2]
    assert out[3].status == Status.OVER_LIMIT


def test_mixed_algorithms_sharded(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    rs = [req(f"t{i}", created_at=t) for i in range(20)] + [
        req(f"l{i}", algorithm=Algorithm.LEAKY_BUCKET, duration=10_000, created_at=t)
        for i in range(20)
    ]
    out = eng.check(rs, now_ms=t)
    assert all(r.remaining == 9 for r in out)


def test_stats_aggregate_across_shards(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    eng.check([req(f"s{i}", created_at=t) for i in range(64)], now_ms=t)
    assert eng.stats.cache_misses == 64
    eng.check([req(f"s{i}", created_at=t) for i in range(64)], now_ms=t)
    assert eng.stats.cache_hits == 64
