"""Multi-device sharding tests on the virtual 8-device CPU mesh.

The analog of the reference's in-process cluster suite (cluster/cluster.go
boots N daemons; functional_test.go drives owner and non-owner nodes): here the
"cluster" is the device mesh, ownership is fingerprint→shard routing, and one
shard_map dispatch serves all shards at once.
"""

import numpy as np
import pytest

import jax

from gubernator_tpu.parallel import ShardedEngine, make_mesh
from gubernator_tpu.parallel.mesh import shard_of
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status, MINUTE


def req(key, hits=1, limit=10, duration=MINUTE, algorithm=Algorithm.TOKEN_BUCKET,
        created_at=None):
    return RateLimitRequest(
        name="sh", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=algorithm, created_at=created_at,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


def test_all_shards_receive_and_persist(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    keys = [f"k{i}" for i in range(256)]
    out = eng.check([req(k, created_at=t) for k in keys], now_ms=t)
    assert all(r.status == Status.UNDER_LIMIT and r.remaining == 9 for r in out)
    # all shards actually hold keys (fingerprints spread over 8 shards)
    from gubernator_tpu.ops.batch import pack_requests
    hb, _ = pack_requests([req(k, created_at=t) for k in keys], t)
    shards = shard_of(hb.fp, 8)
    assert len(set(shards.tolist())) == 8
    # second round decrements every key on its shard
    out = eng.check([req(k, created_at=t) for k in keys], now_ms=t)
    assert all(r.remaining == 8 for r in out)


def test_sequential_semantics_across_shards(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    # duplicate keys + distinct keys mixed in one call
    rs = [req("dup", hits=4, limit=10, created_at=t),
          req("other", hits=1, limit=5, created_at=t),
          req("dup", hits=4, limit=10, created_at=t),
          req("dup", hits=4, limit=10, created_at=t)]
    out = eng.check(rs, now_ms=t)
    assert [r.remaining for r in out] == [6, 4, 2, 2]
    assert out[3].status == Status.OVER_LIMIT


def test_mixed_algorithms_sharded(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    rs = [req(f"t{i}", created_at=t) for i in range(20)] + [
        req(f"l{i}", algorithm=Algorithm.LEAKY_BUCKET, duration=10_000, created_at=t)
        for i in range(20)
    ]
    out = eng.check(rs, now_ms=t)
    assert all(r.remaining == 9 for r in out)


def test_stats_aggregate_across_shards(mesh, frozen_now):
    eng = ShardedEngine(mesh, capacity_per_shard=1024)
    t = frozen_now
    eng.check([req(f"s{i}", created_at=t) for i in range(64)], now_ms=t)
    assert eng.stats.cache_misses == 64
    eng.check([req(f"s{i}", created_at=t) for i in range(64)], now_ms=t)
    assert eng.stats.cache_hits == 64


def test_zipf_skew_routes_balanced(mesh, frozen_now):
    """Zipf-skewed traffic must not skew the shard grid: duplicates aggregate
    in the pass planner (ops/plan.py), so each dispatch routes UNIQUE
    fingerprints whose hash spread is near-multinomial — the padded per-shard
    width stays close to n/D even when one key carries most of the traffic
    (r3 verdict weak #4)."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.parallel.sharded import _route_plan

    rng = np.random.default_rng(11)
    t = frozen_now
    # 4096 requests over ~600 distinct keys, zipf-1.1 (hottest key ~14%)
    z = np.minimum(rng.zipf(1.1, size=4096) - 1, 4095)
    reqs = [req(f"z{k}", hits=1, limit=1 << 20, created_at=t) for k in z]
    eng = ShardedEngine(mesh, capacity_per_shard=4096)
    out = eng.check(reqs, now_ms=t)
    assert all(r.error == "" for r in out)
    # per-key totals decrement sequentially regardless of shard
    uniq, counts = np.unique(z, return_counts=True)
    again = eng.check(
        [req(f"z{k}", hits=0, limit=1 << 20, created_at=t) for k in uniq],
        now_ms=t,
    )
    for k, c, r in zip(uniq, counts, again):
        assert r.remaining == (1 << 20) - c, f"key z{k}"
    # routing balance of the unique-fp pass: padded width within 2x of ideal
    cols = columns_from_requests([req(f"z{k}", created_at=t) for k in uniq])
    from gubernator_tpu.ops.batch import pack_columns

    hb, _ = pack_columns(cols, t)
    routed = shard_of(hb.fp, 8)
    _, _, _, b_local = _route_plan(routed, 8)
    ideal = int(np.ceil(len(uniq) / 8))
    assert b_local <= 2 * ideal, (b_local, ideal)


def test_device_route_matches_host_route(mesh, frozen_now):
    """route="device" (arrival-order rows, on-mesh all_to_all exchange —
    parallel/a2a.py) must serve byte-identical responses and stats to the
    host-routed ownership grid."""
    t = frozen_now
    host_eng = ShardedEngine(mesh, capacity_per_shard=2048, route="host")
    dev_eng = ShardedEngine(mesh, capacity_per_shard=2048, route="device")
    rng = np.random.default_rng(3)
    for step in range(3):
        ks = rng.integers(0, 500, size=200)
        reqs = [
            req(
                f"a{k}",
                hits=1 + int(k) % 3,
                limit=1000,
                algorithm=(
                    Algorithm.TOKEN_BUCKET if k % 3 else Algorithm.LEAKY_BUCKET
                ),
                created_at=t + step,
            )
            for k in ks
        ]
        want = host_eng.check(reqs, now_ms=t + step)
        got = dev_eng.check(reqs, now_ms=t + step)
        for i, (a, b) in enumerate(zip(want, got)):
            assert (a.status, a.remaining, a.reset_time, a.error) == (
                b.status, b.remaining, b.reset_time, b.error,
            ), f"row {i} step {step}"
    assert dev_eng.stats.cache_hits == host_eng.stats.cache_hits
    assert dev_eng.stats.cache_misses == host_eng.stats.cache_misses
    # authoritative state converged identically on every shard
    np.testing.assert_array_equal(host_eng.snapshot(), dev_eng.snapshot())


def test_device_route_capacity_overflow_retries(mesh, frozen_now):
    """A same-owner flood exceeds the per-(src,dst) exchange capacity; the
    dropped rows must re-dispatch (claim-retry path) and hit conservation
    must hold: the bucket's consumed count equals the hits of rows that
    reported success."""
    t = frozen_now
    eng = ShardedEngine(mesh, capacity_per_shard=4096, route="device")
    # craft keys all owned by one shard: shard_of uses fp's high bits
    from gubernator_tpu.ops.batch import fingerprint_columns

    N = 6000
    names = np.array(["sh"] * N, dtype=object)  # req() uses name="sh"
    keys = np.array([f"k{i}" for i in range(N)], dtype=object)
    fps, _ = fingerprint_columns(names, keys)
    shards = shard_of(fps, 8)
    target = int(shards[0])
    picked = [f"k{i}" for i in range(N) if int(shards[i]) == target][:512]
    assert len(picked) == 512
    reqs = [req(k, hits=1, limit=10, created_at=t) for k in picked]
    out = eng.check(reqs, now_ms=t)
    ok = [r for r in out if r.error == ""]
    failed = [r for r in out if r.error != ""]
    # the flood routes through retries; every row must resolve one way
    assert len(ok) + len(failed) == 512
    # the FINAL retry falls back to host ownership routing, so exchange
    # capacity can never fail a valid request (the reference never rejects
    # on internal capacity); only claim contention could, and distinct
    # fresh keys have none
    assert failed == []
    for r in ok:
        assert r.remaining == 9  # distinct keys: each consumed exactly once
    # stat conservation across the retry chain: every key fresh and
    # distinct → each row is exactly one miss, counted at the dispatch that
    # first PROCESSES it (capacity-dropped rows count at their retry),
    # never twice, never as a hit — and the full identity holds:
    # checks == hits + misses + terminally-unprocessed
    assert eng.stats.cache_hits == 0
    assert eng.stats.cache_misses == 512
    assert eng.stats.unprocessed_dropped == 0
    assert eng.stats.checks == (
        eng.stats.cache_hits
        + eng.stats.cache_misses
        + eng.stats.unprocessed_dropped
    )


def test_device_route_terminal_unprocessed_counted(mesh, frozen_now):
    """Rows that exhaust the retry budget while still FLAG_UNPROCESSED (a2a
    capacity drops that never reached a kernel) must be visible in the
    dedicated unprocessed_dropped counter — entering the dispatch at the
    terminal depth disables both the retries and the host fallback, so
    capacity drops surface immediately."""
    from gubernator_tpu.ops.batch import fingerprint_columns, pack_requests

    t = frozen_now
    eng = ShardedEngine(mesh, capacity_per_shard=4096, route="device")
    N = 6000
    names = np.array(["sh"] * N, dtype=object)
    keys = np.array([f"k{i}" for i in range(N)], dtype=object)
    fps, _ = fingerprint_columns(names, keys)
    shards = shard_of(fps, 8)
    target = int(shards[0])
    picked = [f"k{i}" for i in range(N) if int(shards[i]) == target][:512]
    reqs = [req(k, hits=1, limit=10, created_at=t) for k in picked]
    hb, _errs = pack_requests(reqs, t)
    _, (s, l, r, tt, dropped, h) = eng._dispatch(
        hb, depth=3, count=np.asarray(hb.active)
    )
    assert dropped.any()  # the same-owner flood exceeds pair capacity
    assert eng.stats.unprocessed_dropped == int(dropped.sum())
    assert eng.stats.dropped == int(dropped.sum())
    # identity: every counted row is a hit, a miss, or terminally-unprocessed
    assert int(np.asarray(hb.active).sum()) == (
        eng.stats.cache_hits
        + eng.stats.cache_misses
        + eng.stats.unprocessed_dropped
    )


def test_sharded_pipeline_matches_serial(mesh, frozen_now):
    """The prepare/issue/finish split (served by the pipelined front door)
    must produce byte-identical responses to the serial sharded path."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    t = frozen_now
    reqs = [req(f"p{i % 48}", hits=1 + i % 3, limit=100, created_at=t)
            for i in range(160)]
    cols = columns_from_requests(reqs)
    serial = ShardedEngine(mesh, capacity_per_shard=1024)
    piped = ShardedEngine(mesh, capacity_per_shard=1024)
    assert piped.supports_pipeline
    for _ in range(3):
        want = serial.check_columns(cols, now_ms=t)
        pending = issue_check_columns(piped, prepare_check_columns(piped, cols, now_ms=t))
        got, delta = finish_check_columns(piped, pending, fixup=lambda fn: fn())
        piped.stats.merge(delta)
        np.testing.assert_array_equal(got.status, want.status)
        np.testing.assert_array_equal(got.remaining, want.remaining)
        np.testing.assert_array_equal(got.reset_time, want.reset_time)
        np.testing.assert_array_equal(got.err, want.err)
    assert piped.stats.cache_hits == serial.stats.cache_hits
    assert piped.stats.cache_misses == serial.stats.cache_misses


def test_pipelined_multi_pass_single_fetch(mesh, frozen_now):
    """A hot-key batch plans max_exact same-shape passes; the pipelined path
    must fuse their outputs into ONE stacked fetch (pending.stacked) and
    still produce responses identical to the serial path — on the tunneled
    platform each fetch is a serialized round trip, so without the fuse a
    herd request pays max_exact round trips."""
    from gubernator_tpu.ops.batch import columns_from_requests
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )

    t = frozen_now
    reqs = [req("herd", hits=1, limit=1 << 20, created_at=t) for _ in range(64)]
    cols = columns_from_requests(reqs)

    serial = ShardedEngine(mesh, capacity_per_shard=2048)
    rc_serial = serial.check_columns(cols, now_ms=t)

    piped = ShardedEngine(mesh, capacity_per_shard=2048)
    pending = prepare_check_columns(piped, cols, now_ms=t)
    assert len(pending.passes) > 1  # herd → multiple sequential passes
    pending = issue_check_columns(piped, pending)
    assert pending.stacked is not None  # same-shape passes fused
    rc_piped, delta = finish_check_columns(piped, pending, lambda fn: fn())
    piped.stats.merge(delta)

    np.testing.assert_array_equal(rc_piped.status, rc_serial.status)
    np.testing.assert_array_equal(rc_piped.remaining, rc_serial.remaining)
    np.testing.assert_array_equal(rc_piped.err, rc_serial.err)
    assert serial.stats.cache_hits == piped.stats.cache_hits
    assert serial.stats.cache_misses == piped.stats.cache_misses
    np.testing.assert_array_equal(serial.snapshot(), piped.snapshot())
