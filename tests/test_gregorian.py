"""Gregorian-calendar expiration units — every granularity at its boundary
(reference TestGregorianExpirationMinute/Hour/Day/Month/Year/Invalid,
config_test.go; semantics from interval.go:84-148).

The module computes in the HOST's local timezone (like the reference's Go
time package), so assertions reconstruct boundaries with datetime rather
than hard-coding epoch values.
"""

import datetime as dt
import os
import time

import pytest

from gubernator_tpu.gregorian import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.types import Gregorian


@pytest.fixture(autouse=True)
def utc_tz():
    """Pin the process timezone: the module computes in host-local time (like
    the reference's Go time package), and DST transitions change month/year
    lengths by an hour — these boundary assertions need a DST-free zone."""
    old = os.environ.get("TZ")
    os.environ["TZ"] = "UTC"
    time.tzset()
    yield
    if old is None:
        os.environ.pop("TZ", None)
    else:
        os.environ["TZ"] = old
    time.tzset()

# fixed instant: 2023-11-14 ~22:13:20.987 UTC, mid-minute/-hour/-day
NOW = 1_700_000_000_987


def _local(ms: int) -> dt.datetime:
    return dt.datetime.fromtimestamp(ms / 1000.0).astimezone()


@pytest.mark.parametrize(
    "granularity,length_ms",
    [
        (Gregorian.MINUTES, 60_000),
        (Gregorian.HOURS, 3_600_000),
        (Gregorian.DAYS, 86_400_000),
    ],
)
def test_fixed_length_intervals(granularity, length_ms):
    assert gregorian_duration(NOW, granularity) == length_ms
    exp = gregorian_expiration(NOW, granularity)
    # expiry is the LAST ms inside the interval containing NOW...
    assert NOW <= exp < NOW + length_ms
    # ...and exp+1 is an exact interval boundary in local time
    b = _local(exp + 1)
    assert (b.second, b.microsecond) == (0, 0)
    if granularity != Gregorian.MINUTES:
        assert b.minute == 0
    if granularity == Gregorian.DAYS:
        assert b.hour == 0


def test_month_interval():
    exp = gregorian_expiration(NOW, Gregorian.MONTHS)
    assert exp >= NOW
    b = _local(exp + 1)
    assert (b.day, b.hour, b.minute, b.second, b.microsecond) == (1, 0, 0, 0, 0)
    # the duration is this month's real length (28-31 days worth of ms)
    dur = gregorian_duration(NOW, Gregorian.MONTHS)
    assert dur in {d * 86_400_000 for d in (28, 29, 30, 31)}
    # expiry sits exactly at month-begin + month-length - 1
    n = _local(NOW)
    begin = n.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    assert exp == int(begin.timestamp() * 1000) + dur - 1


def test_year_interval():
    exp = gregorian_expiration(NOW, Gregorian.YEARS)
    b = _local(exp + 1)
    assert (b.month, b.day, b.hour, b.minute) == (1, 1, 0, 0)
    dur = gregorian_duration(NOW, Gregorian.YEARS)
    assert dur in {365 * 86_400_000, 366 * 86_400_000}


def test_leap_year_february():
    # 2024-02-10 12:00:00 UTC — February of a leap year is 29 days
    feb_2024 = int(dt.datetime(2024, 2, 10, 12, 0, 0).timestamp() * 1000)
    assert gregorian_duration(feb_2024, Gregorian.MONTHS) == 29 * 86_400_000
    assert gregorian_duration(feb_2024, Gregorian.YEARS) == 366 * 86_400_000


def test_december_rolls_into_next_year():
    dec = int(dt.datetime(2023, 12, 31, 23, 59, 59).timestamp() * 1000)
    exp = gregorian_expiration(dec, Gregorian.MONTHS)
    b = _local(exp + 1)
    assert (b.year, b.month, b.day) == (2024, 1, 1)


def test_weeks_and_invalid_rejected():
    # reference interval.go:88-89 rejects weeks; anything else is invalid
    with pytest.raises(GregorianError):
        gregorian_duration(NOW, Gregorian.WEEKS)
    with pytest.raises(GregorianError):
        gregorian_expiration(NOW, Gregorian.WEEKS)
    with pytest.raises(GregorianError):
        gregorian_duration(NOW, 999)
    with pytest.raises(GregorianError):
        gregorian_expiration(NOW, 999)
