"""Functional tests through the real front door — gRPC + HTTP on live daemons.

The analog of the reference's black-box functional suite
(functional_test.go): every assertion goes through a running daemon's real
listeners (in-process cluster fixture, tests/cluster.py)."""

import asyncio
import functools

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status

from tests.cluster import Cluster, metric_value, scrape, daemon_config, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="svc", hits=1, limit=5, duration=60_000, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=duration, **kw
    )


# ---------------------------------------------------------------- single node


@async_test
async def test_single_daemon_over_limit_via_grpc():
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        for expect_remaining, expect_status in [
            (4, Status.UNDER_LIMIT),
            (3, Status.UNDER_LIMIT),
            (2, Status.UNDER_LIMIT),
            (1, Status.UNDER_LIMIT),
            (0, Status.UNDER_LIMIT),
            (0, Status.OVER_LIMIT),
        ]:
            resp = await client.get_rate_limits([req("grpc1")])
            (r,) = resp.responses
            assert r.error == ""
            assert r.remaining == expect_remaining
            assert r.status == int(expect_status)
    finally:
        await client.close()
        await d.close()


@async_test
async def test_request_order_and_per_item_errors():
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        resp = await client.get_rate_limits(
            [
                req("ok1"),
                req(""),  # empty key → per-item error
                RateLimitRequest(name="", unique_key="x", hits=1, limit=5, duration=60_000),
                req("ok2"),
            ]
        )
        rs = resp.responses
        assert len(rs) == 4
        assert rs[0].error == "" and rs[0].remaining == 4
        assert rs[1].error == "field 'unique_key' cannot be empty"
        assert rs[2].error == "field 'namespace' cannot be empty"
        assert rs[3].error == "" and rs[3].remaining == 4
    finally:
        await client.close()
        await d.close()


@async_test
async def test_batch_too_large_rejected():
    import grpc

    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        with pytest.raises(grpc.aio.AioRpcError) as e:
            await client.get_rate_limits([req(f"k{i}") for i in range(1001)])
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        await client.close()
        await d.close()


@async_test
async def test_http_gateway_json():
    """HTTP JSON gateway with proto field names (reference TestGRPCGateway,
    functional_test.go:1622)."""
    import aiohttp

    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    try:
        base = f"http://{d.conf.http_address}"
        async with aiohttp.ClientSession() as s:
            body = {
                "requests": [
                    {
                        "name": "http",
                        "unique_key": "j1",
                        "hits": 1,
                        "limit": 10,
                        "duration": 60000,
                    }
                ]
            }
            async with s.post(f"{base}/v1/GetRateLimits", json=body) as resp:
                assert resp.status == 200
                out = await resp.json()
            assert "responses" in out
            r = out["responses"][0]
            # proto names preserved (UseProtoNames, daemon.go:267-273)
            assert r["remaining"] == "9"
            assert "reset_time" in r
            async with s.get(f"{base}/v1/HealthCheck") as resp:
                health = await resp.json()
            assert health["status"] == "healthy"
            async with s.get(f"{base}/v1/LiveCheck") as resp:
                assert resp.status == 200
            async with s.get(f"{base}/metrics") as resp:
                text = await resp.text()
            assert "gubernator_grpc_request_counts" in text
            assert "gubernator_cache_size" in text
    finally:
        await d.close()


@async_test
async def test_batching_coalesces_concurrent_requests():
    """Concurrent requests inside one BatchWait window land in one device
    dispatch (the 500µs coalescing mechanic, peer_client.go:289-344 analog)."""
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    # generous timeout: the coalesced batch shape compiles on first use
    client = V1Client(d.conf.grpc_address, timeout_s=30.0)
    try:
        before = d.engine.stats.dispatches
        out = await asyncio.gather(
            *(client.get_rate_limits([req(f"co{i}")]) for i in range(32))
        )
        for resp in out:
            assert resp.responses[0].remaining == 4
        used = d.engine.stats.dispatches - before
        assert used < 32, f"no coalescing: {used} dispatches for 32 requests"
    finally:
        await client.close()
        await d.close()


# ------------------------------------------------------------------- cluster


@async_test
async def test_cluster_forwarding_owner_consistency():
    """Hits on one key from every daemon must serialize on the owner: the
    remaining count is globally consistent (reference TestMultipleAsync,
    functional_test.go:115)."""
    c = await Cluster.start(3)
    clients = [V1Client(d.conf.grpc_address) for d in c.daemons]
    try:
        remaining = []
        for i, client in enumerate(clients * 2):  # 6 hits round-robin
            resp = await client.get_rate_limits([req("fwd-key", limit=10)])
            (r,) = resp.responses
            assert r.error == ""
            remaining.append(r.remaining)
        assert remaining == [9, 8, 7, 6, 5, 4]
        # the owner executed them all
        owner = c.find_owning_daemon("svc", "fwd-key")
        assert owner.engine.stats.checks >= 6
        for d in c.non_owning_daemons("svc", "fwd-key"):
            assert d.engine.stats.checks == 0
    finally:
        for cl in clients:
            await cl.close()
        await c.stop()


@async_test
async def test_cluster_health_and_peer_count():
    c = await Cluster.start(3)
    client = V1Client(c.daemons[0].conf.grpc_address)
    try:
        h = await client.health_check()
        assert h.status == "healthy"
        assert h.peer_count == 3
        assert len(h.local_peers) == 3
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_set_peers_moves_ownership():
    """Shrinking the peer set re-routes keys (reference SetPeers hot swap,
    gubernator.go:694-789)."""
    c = await Cluster.start(3)
    client = V1Client(c.daemons[0].conf.grpc_address)
    try:
        resp = await client.get_rate_limits([req("move-key", limit=10)])
        assert resp.responses[0].remaining == 9
        # drop to a single-peer cluster: daemon 0 owns everything
        from gubernator_tpu.types import PeerInfo

        solo = [c.daemons[0].peer_info()]
        for d in c.daemons:
            d.set_peers([PeerInfo(**vars(p)) for p in solo])
        resp = await client.get_rate_limits([req("move-key", limit=10)])
        r = resp.responses[0]
        assert r.error == ""
        # daemon 0 now owns the key; whether state was preserved depends on
        # who owned it before (cache loss on reshard is the accepted model,
        # docs/architecture.md:5-11) — the contract is it still answers
        assert r.remaining in (8, 9)
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_cluster_scrape_request_counters():
    """Counters travel the real /metrics endpoint (reference getMetrics)."""
    c = await Cluster.start(2)
    client = V1Client(c.daemons[0].conf.grpc_address)
    try:
        await client.get_rate_limits([req("m1"), req("m2")])
        scraped = await scrape(c.daemons[0])
        got = metric_value(
            scraped,
            "gubernator_grpc_request_counts_total",
            method="/v1.GetRateLimits",
        )
        assert got == 1.0
    finally:
        await client.close()
        await c.stop()


@async_test
async def test_peer_client_shutdown_races_inflight_requests():
    """In-flight forwarded requests race Shutdown: each either completes or
    fails with a peer error — never hangs, never loses its future (reference
    TestPeerClientShutdown, peer_client_test.go:33)."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.peer_client import PeerClient, PeerError
    from gubernator_tpu.types import PeerInfo

    d = await Daemon.spawn(daemon_config())
    try:
        client = PeerClient(
            PeerInfo(grpc_address=d.conf.grpc_address),
            batch_wait_ms=5.0,  # wide window so shutdown races the flush
            batch_timeout_ms=5000.0,
        )

        async def one(i):
            try:
                r = await client.get_peer_rate_limit(
                    pb.RateLimitReq(
                        name="shut", unique_key=f"k{i}", hits=1, limit=100,
                        duration=60_000,
                    )
                )
                return ("ok", r.remaining)
            except PeerError:
                return ("err", None)

        tasks = [asyncio.create_task(one(i)) for i in range(50)]
        await asyncio.sleep(0.001)
        await client.shutdown()
        results = await asyncio.wait_for(asyncio.gather(*tasks), timeout=30)
        assert len(results) == 50
        oks = [r for r in results if r[0] == "ok"]
        # the pre-shutdown flush drains queued requests; everything resolved
        assert all(r[1] == 99 for r in oks)
    finally:
        await d.close()


@async_test
async def test_peer_client_residual_queue_and_midsend_enqueue_drain():
    """2× batch_limit enqueued in one burst plus an enqueue landing while a
    send is in flight, then silence: the long-lived flush loop must drain
    everything without cancelling an in-flight batch or stranding a future
    (reference runBatch, peer_client.go:289-344 — the one-shot-task design
    this replaced could self-cancel mid-RPC and strand quiet-period items)."""
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.peer_client import PeerClient
    from gubernator_tpu.types import PeerInfo

    d = await Daemon.spawn(daemon_config())
    client = PeerClient(
        PeerInfo(grpc_address=d.conf.grpc_address),
        batch_wait_ms=1.0,
        batch_limit=8,  # small limit so 16 items need multiple chunks
        batch_timeout_ms=5000.0,
    )
    try:
        async def one(i):
            r = await client.get_peer_rate_limit(
                pb.RateLimitReq(
                    name="drain", unique_key=f"k{i}", hits=1, limit=100,
                    duration=60_000,
                )
            )
            return r.remaining

        tasks = [asyncio.create_task(one(i)) for i in range(16)]
        await asyncio.sleep(0)  # let the burst enqueue
        # mid-send enqueue: wait for an in-flight send, then add one more
        for _ in range(5000):
            if client._inflight:
                break
            await asyncio.sleep(0.001)
        tasks.append(asyncio.create_task(one(99)))
        # go quiet: every future must resolve (no PeerError → gather raises)
        results = await asyncio.wait_for(asyncio.gather(*tasks), timeout=20)
        assert len(results) == 17
        assert all(r == 99 for r in results)  # unique keys, one hit each
        assert not client._queue  # nothing stranded
    finally:
        await client.shutdown()
        assert client._loop_task is None or client._loop_task.done()
        await d.close()


@async_test
async def test_daemon_close_leaves_no_running_tasks():
    """Graceful close cancels every loop the daemon started (the goleak
    analog, reference lrucache_test.go via go.uber.org/goleak)."""
    from gubernator_tpu.service.daemon import Daemon

    before = {id(t) for t in asyncio.all_tasks()}
    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        await client.get_rate_limits([req("leak")])
    finally:
        await client.close()
        await d.close()
    await asyncio.sleep(0.1)
    leaked = [
        t for t in asyncio.all_tasks()
        if id(t) not in before and not t.done()
        and t is not asyncio.current_task()
    ]
    assert not leaked, [t.get_name() for t in leaked]


@async_test
async def test_oversize_message_rejected_by_transport():
    """The public gRPC server caps receive size at 1 MiB (reference
    daemon.go:133 MaxRecvMsgSize): a wire-legal batch inflated past the cap
    must be refused at the transport with RESOURCE_EXHAUSTED, before any
    handler work."""
    import grpc

    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(daemon_config())
    client = V1Client(d.conf.grpc_address)
    try:
        big = "x" * 1500
        with pytest.raises(grpc.aio.AioRpcError) as e:
            await client.get_rate_limits(
                [req(f"{big}{i}") for i in range(1000)]
            )
        assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    finally:
        await client.close()
        await d.close()
