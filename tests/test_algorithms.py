"""ISSUE-10 algorithm-breadth suite: GCRA, sliding-window counters,
concurrency leases, and cascaded multi-limit checks.

Parity contract: every device implementation (LocalEngine full-width +
compact wire, 8-device ShardedEngine with device routing/dedup) must match
the pure-Python oracles in tests/oracle/algos.py decision-for-decision
across randomized schedules. Conservatism contract: checkpoint/handoff
replay through kernel2.merge2 can only UNDER-grant (stale GCRA TAT, stale
window counts). Cascade contract: deny-if-any, per-level responses,
(fp, level) dedup discrimination, single-dispatch evaluation.
"""

from __future__ import annotations

import asyncio
import functools

import numpy as np
import pytest

from gubernator_tpu.hashing import fingerprint
from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import Algorithm, RateLimitRequest
from tests.oracle.algos import (
    GcraOracle,
    LeaseOracle,
    SlidingWindowOracle,
    TokenOracle,
)

NOW = 1_700_000_000_000


def _cols(keys, algo, hits, limit, duration, now, burst=None, levels=None):
    n = len(keys)
    return RequestColumns(
        fp=np.array([fingerprint("alg", k) for k in keys], dtype=np.int64),
        algo=np.full(n, int(algo), dtype=np.int32),
        behavior=np.array(
            [(lvl << 8) for lvl in (levels or [0] * n)], dtype=np.int32
        ),
        hits=np.asarray(hits, dtype=np.int64),
        limit=np.asarray(limit, dtype=np.int64),
        burst=np.asarray(
            burst if burst is not None else np.zeros(n), dtype=np.int64
        ),
        duration=np.asarray(duration, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def _engines(request_mesh=None):
    """The device implementations under parity test."""
    engines = [
        ("local-full", LocalEngine(capacity=1 << 14, write_mode="xla", wire="full")),
        ("local-compact", LocalEngine(capacity=1 << 14, write_mode="xla", wire="compact")),
    ]
    if request_mesh is not None:
        from gubernator_tpu.parallel.sharded import ShardedEngine

        engines.append((
            "sharded-8dev",
            ShardedEngine(
                request_mesh, capacity_per_shard=1 << 12,
                route="device", dedup="device",
            ),
        ))
    return engines


@pytest.fixture
def mesh():
    import jax

    from gubernator_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) == 8, "tests require the 8-device CPU mesh"
    return make_mesh(8)


# ------------------------------------------------------------------ GCRA


def _gcra_schedule(rng, n_steps=40, n_keys=6):
    """Randomized (dt, key, hits) schedule with a mix of conforming and
    bursty arrivals."""
    t = NOW
    steps = []
    for _ in range(n_steps):
        t += int(rng.integers(0, 1500))
        keys = [f"g{int(k)}" for k in rng.choice(n_keys, size=rng.integers(1, 4), replace=False)]
        hits = [int(rng.integers(0, 5)) for _ in keys]
        steps.append((t, keys, hits))
    return steps


@pytest.mark.parametrize("wire", ["full", "compact"])
def test_gcra_oracle_parity_local(wire):
    rng = np.random.default_rng(7)
    eng = LocalEngine(capacity=1 << 14, write_mode="xla", wire=wire)
    oracle = GcraOracle()
    limit, dur = 10, 10_000
    for t, keys, hits in _gcra_schedule(rng):
        rc = eng.check_columns(
            _cols(keys, Algorithm.GCRA, hits, [limit] * len(keys),
                  [dur] * len(keys), t),
            now_ms=t,
        )
        for j, k in enumerate(keys):
            st, rem, reset = oracle.check(
                fingerprint("alg", k), t, hits[j], limit, dur
            )
            assert (int(rc.status[j]), int(rc.remaining[j]), int(rc.reset_time[j])) == (
                st, rem, reset
            ), (k, t, hits[j])


def test_gcra_oracle_parity_mesh(mesh):
    from gubernator_tpu.parallel.sharded import ShardedEngine

    rng = np.random.default_rng(11)
    eng = ShardedEngine(mesh, capacity_per_shard=1 << 12, route="device",
                        dedup="device")
    oracle = GcraOracle()
    limit, dur = 12, 6_000
    for t, keys, hits in _gcra_schedule(rng, n_steps=25, n_keys=24):
        rc = eng.check_columns(
            _cols(keys, Algorithm.GCRA, hits, [limit] * len(keys),
                  [dur] * len(keys), t),
            now_ms=t,
        )
        for j, k in enumerate(keys):
            st, rem, reset = oracle.check(
                fingerprint("alg", k), t, hits[j], limit, dur
            )
            assert (int(rc.status[j]), int(rc.remaining[j]), int(rc.reset_time[j])) == (
                st, rem, reset
            ), (k, t)


def test_gcra_token_equivalence_at_burst_limit():
    """With burst == limit, GCRA and the reference token bucket admit the
    same instant burst (exactly `limit` unit hits) and converge to the same
    long-run admission rate (limit per duration): across a randomized
    overloaded schedule the cumulative admitted counts never diverge by
    more than one burst."""
    rng = np.random.default_rng(13)
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    tok = TokenOracle()
    limit, dur = 8, 8_000
    # instant burst: exactly `limit` admitted by both
    t = NOW
    g_admit = t_admit = 0
    for i in range(limit + 4):
        rc = eng.check_columns(
            _cols(["ge"], Algorithm.GCRA, [1], [limit], [dur], t), now_ms=t
        )
        g_admit += int(rc.status[0]) == 0
        st, _ = tok.check(1, t, 1, limit, dur)
        t_admit += st == 0
    assert g_admit == t_admit == limit
    # randomized OVERLOADED schedule (arrivals ~2× the sustainable rate):
    # both enforce the same long-run admission rate — limit per duration —
    # GCRA smoothly (1 per T), token in window steps, so the cumulative
    # admitted counts track within two windows' worth of quantization
    g_total = t_total = 0
    t0 = t
    for _ in range(400):
        t += int(rng.integers(0, dur // limit))
        rc = eng.check_columns(
            _cols(["gr"], Algorithm.GCRA, [1], [limit], [dur], t), now_ms=t
        )
        g_total += int(rc.status[0]) == 0
        st, _ = tok.check(2, t, 1, limit, dur)
        t_total += st == 0
    assert abs(g_total - t_total) <= 2 * limit, (g_total, t_total)
    # and both sit at the configured rate (±1 window) over the elapsed span
    expected = (t - t0) * limit // dur
    assert abs(g_total - expected) <= 2 * limit, (g_total, expected)


def test_gcra_drain_and_reset():
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    limit, dur = 5, 5_000

    def one(key, hits, behavior, t):
        return eng.check(
            [RateLimitRequest(name="alg", unique_key=key, hits=hits,
                              limit=limit, duration=dur,
                              algorithm=Algorithm.GCRA, behavior=behavior,
                              created_at=t)],
            now_ms=t,
        )[0]

    # DRAIN_OVER_LIMIT: a denied request empties the tolerance
    assert one("d", 3, 0, NOW).status == 0
    r = one("d", 4, 32, NOW)  # 3+4 > 5 → deny, drain
    assert r.status == 1 and r.remaining == 0
    # RESET_REMAINING removes the item and reports a full bucket
    r = one("d", 0, 8, NOW)
    assert r.status == 0 and r.remaining == limit
    assert one("d", limit, 0, NOW).status == 0  # full again


# --------------------------------------------------------- sliding window


@pytest.mark.parametrize("wire", ["full", "compact"])
def test_sliding_window_boundary_parity(wire):
    """Window-boundary crossings: the interpolated carry-over from the
    previous window must match the oracle hit-for-hit, including the roll
    into an empty middle window and full staleness two windows later."""
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire=wire)
    oracle = SlidingWindowOracle()
    limit, dur = 10, 10_000
    fp = fingerprint("alg", "w")
    # timestamps chosen to land before/on/after boundaries
    base = (NOW // dur) * dur
    times = [
        base + 100, base + 9_900, base + dur, base + dur + 2_500,
        base + dur + 9_999, base + 2 * dur + 1, base + 4 * dur + 7,
    ]
    hits = [4, 5, 3, 2, 6, 1, 2]
    for t, h in zip(times, hits):
        rc = eng.check_columns(
            _cols(["w"], Algorithm.SLIDING_WINDOW, [h], [limit], [dur], t),
            now_ms=t,
        )
        st, rem, reset = oracle.check(fp, t, h, limit, dur)
        assert (int(rc.status[0]), int(rc.remaining[0]), int(rc.reset_time[0])) == (
            st, rem, reset
        ), t


def test_sliding_window_randomized_parity_mesh(mesh):
    from gubernator_tpu.parallel.sharded import ShardedEngine

    rng = np.random.default_rng(17)
    eng = ShardedEngine(mesh, capacity_per_shard=1 << 12, route="device",
                        dedup="device")
    oracle = SlidingWindowOracle()
    limit, dur = 9, 4_000
    t = NOW
    for _ in range(60):
        t += int(rng.integers(0, 3_000))
        keys = [f"w{int(k)}" for k in rng.choice(16, size=3, replace=False)]
        hits = [int(rng.integers(0, 4)) for _ in keys]
        rc = eng.check_columns(
            _cols(keys, Algorithm.SLIDING_WINDOW, hits, [limit] * 3,
                  [dur] * 3, t),
            now_ms=t,
        )
        for j, k in enumerate(keys):
            st, rem, reset = oracle.check(fingerprint("alg", k), t, hits[j],
                                          limit, dur)
            assert (int(rc.status[j]), int(rc.remaining[j])) == (st, rem), (k, t)
            assert int(rc.reset_time[j]) == reset


def test_sliding_window_interpolation_denies_burst_across_boundary():
    """The point of interpolation: a full previous window keeps denying
    just past the boundary (a fixed window would admit a fresh burst)."""
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    limit, dur = 10, 10_000
    base = (NOW // dur) * dur
    rc = eng.check_columns(
        _cols(["b"], Algorithm.SLIDING_WINDOW, [10], [limit], [dur],
              base + 9_000),
        now_ms=base + 9_000,
    )
    assert int(rc.status[0]) == 0
    # 1 ms into the next window: ~100% of the previous window still covered
    rc = eng.check_columns(
        _cols(["b"], Algorithm.SLIDING_WINDOW, [5], [limit], [dur],
              base + dur + 1),
        now_ms=base + dur + 1,
    )
    assert int(rc.status[0]) == 1
    # 90% through the next window the carry has decayed to ~1 → admits
    rc = eng.check_columns(
        _cols(["b"], Algorithm.SLIDING_WINDOW, [5], [limit], [dur],
              base + dur + 9_000),
        now_ms=base + dur + 9_000,
    )
    assert int(rc.status[0]) == 0


# ------------------------------------------------------- concurrency lease


@pytest.mark.parametrize("wire", ["full", "compact"])
def test_lease_acquire_release_expire(wire):
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire=wire)
    oracle = LeaseOracle()
    limit, ttl = 10, 5_000
    fp = fingerprint("alg", "l")
    # note: releases (hits < 0) are not compact-encodable — the engine
    # falls those dispatches back to full-width transparently
    schedule = [
        (NOW, 8), (NOW + 10, 5), (NOW + 20, -6), (NOW + 30, 5),
        (NOW + 40, 0), (NOW + 100, -20), (NOW + 200, limit),
        # expiry reclamation: TTL passes → all leases reclaimed
        (NOW + 200 + ttl + 1, limit),
    ]
    for t, h in schedule:
        rc = eng.check_columns(
            _cols(["l"], Algorithm.CONCURRENCY_LEASE, [h], [limit], [ttl], t),
            now_ms=t,
        )
        st, rem, reset = oracle.check(fp, t, h, limit, ttl)
        assert (int(rc.status[0]), int(rc.remaining[0]), int(rc.reset_time[0])) == (
            st, rem, reset
        ), (t, h)


def test_lease_parity_mesh(mesh):
    from gubernator_tpu.parallel.sharded import ShardedEngine

    rng = np.random.default_rng(23)
    eng = ShardedEngine(mesh, capacity_per_shard=1 << 12, route="device",
                        dedup="device")
    oracle = LeaseOracle()
    limit, ttl = 6, 8_000
    t = NOW
    for _ in range(50):
        t += int(rng.integers(0, 2_000))
        keys = [f"l{int(k)}" for k in rng.choice(10, size=2, replace=False)]
        hits = [int(rng.integers(-3, 4)) for _ in keys]
        rc = eng.check_columns(
            _cols(keys, Algorithm.CONCURRENCY_LEASE, hits, [limit] * 2,
                  [ttl] * 2, t),
            now_ms=t,
        )
        for j, k in enumerate(keys):
            st, rem, reset = oracle.check(fingerprint("alg", k), t, hits[j],
                                          limit, ttl)
            assert (int(rc.status[j]), int(rc.remaining[j])) == (st, rem), (k, t, hits[j])


# ------------------------------------------------- merge/replay conservatism


def test_merge_replay_conservatism_gcra_and_window(frozen_now):
    """Checkpoint/handoff replay (kernel2.merge2) can only UNDER-grant for
    the new lanes: a stale GCRA TAT (smaller) must not roll admission back,
    a duplicated replay must be idempotent, and the same for sliding-window
    counts (REM_I remaining-style min + aux max)."""
    now = frozen_now
    src = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    limit, dur = 10, 10_000
    # consume 4 → snapshot A (tat = now+4T); consume 4 more → snapshot B
    src.check_columns(_cols(["g"], Algorithm.GCRA, [4], [limit], [dur], now), now_ms=now)
    fps_a, slots_a = src.extract_live(now_ms=now)
    src.check_columns(_cols(["g"], Algorithm.GCRA, [4], [limit], [dur], now), now_ms=now)
    src.check_columns(
        _cols(["w"], Algorithm.SLIDING_WINDOW, [7], [limit], [dur], now), now_ms=now
    )
    fps_b, slots_b = src.extract_live(now_ms=now)

    dst = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    # replay NEW then STALE then NEW again (duplicated + out-of-order)
    assert dst.merge_rows(fps_b, slots_b, now_ms=now) == len(fps_b)
    dst.merge_rows(fps_a, slots_a, now_ms=now)
    dst.merge_rows(fps_b, slots_b, now_ms=now)

    # the replayed engine must admit NO MORE than the source engine
    for key, algo in (("g", Algorithm.GCRA), ("w", Algorithm.SLIDING_WINDOW)):
        rc_src = src.check_columns(
            _cols([key], algo, [0], [limit], [dur], now), now_ms=now
        )
        rc_dst = dst.check_columns(
            _cols([key], algo, [0], [limit], [dur], now), now_ms=now
        )
        assert int(rc_dst.remaining[0]) <= int(rc_src.remaining[0]), key
        # and exactly equal here: the newest state won every merge
        assert int(rc_dst.remaining[0]) == int(rc_src.remaining[0]), key


# ---------------------------------------------------------------- cascades


def _cascade_cols(now, user_hits=1, user="u1", tenant="acme"):
    """3-level cascade: per-user token(5/min) + per-tenant window(8/min) +
    global GCRA(50/min) — the API-gateway shape from the ISSUE."""
    keys = [f"user:{user}", f"tenant:{tenant}", "global"]
    n = 3
    return RequestColumns(
        fp=np.array([fingerprint("casc", k) for k in keys], dtype=np.int64),
        algo=np.array([0, int(Algorithm.SLIDING_WINDOW), int(Algorithm.GCRA)],
                      dtype=np.int32),
        behavior=np.array([0, 1 << 8, 2 << 8], dtype=np.int32),
        hits=np.full(n, user_hits, dtype=np.int64),
        limit=np.array([5, 8, 50], dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


@pytest.mark.parametrize("wire", ["full", "compact"])
def test_cascade_deny_if_any_single_dispatch(wire, frozen_now):
    now = frozen_now
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire=wire)
    d0 = eng.stats.dispatches
    rc = eng.check_columns(_cascade_cols(now, user_hits=4), now_ms=now)
    # ONE dispatch evaluated all three levels
    assert eng.stats.dispatches == d0 + 1
    assert int(rc.status[0]) == 0
    # carrier remaining = min across levels (user: 1 left)
    assert int(rc.remaining[0]) == 1
    # per-level rows keep their own responses
    assert int(rc.remaining[1]) == 4 and int(rc.remaining[2]) == 46
    rc = eng.check_columns(_cascade_cols(now, user_hits=4), now_ms=now)
    # user level denies → cascade verdict OVER; tenant level admitted (8)
    assert int(rc.status[0]) == 1
    assert int(rc.status[1]) == 0


def test_cascade_compact_wire_encodable(frozen_now):
    """An encodable 3-level cascade rides the compact wire — zero
    full-width fallbacks (the CI algo_smoke gate's unit twin)."""
    from gubernator_tpu.ops import wire as wire_mod
    from gubernator_tpu.ops.batch import pack_columns

    hb, err = pack_columns(_cascade_cols(NOW), NOW)
    assert not err.any()
    base = wire_mod.pick_base(hb)
    assert wire_mod.wire_encodable(hb, base)
    # roundtrip: host decode == original fields (incl. level bits)
    lanes = wire_mod.pack_wire_rows(hb, base)
    dec = wire_mod.decode_wire_host(lanes, base)
    np.testing.assert_array_equal(dec["fp"], hb.fp)
    np.testing.assert_array_equal(dec["algo"], hb.algo)
    np.testing.assert_array_equal(
        (dec["behavior"] >> 8) & 0xFF, [0, 1, 2]
    )
    np.testing.assert_array_equal(dec["limit"], hb.limit)
    # deeper than the 2-bit lane budget → full-width fallback
    deep = hb._replace(behavior=hb.behavior | np.int32(4 << 8))
    assert not wire_mod.wire_encodable(deep, base)


def test_cascade_fp_level_collision_regression(frozen_now, mesh):
    """The (fp, level) dedup discriminator: the SAME key at two levels of
    one cascade must evaluate BOTH limit configs (sequential semantics via
    the claim-conflict retry), not silently merge into one row whose
    newest config clobbers the other — on the host planner AND the
    in-trace device dedup."""
    from gubernator_tpu.parallel.sharded import ShardedEngine

    now = NOW
    key = fingerprint("casc", "clash")

    def batch():
        return RequestColumns(
            fp=np.array([key, key], dtype=np.int64),
            algo=np.zeros(2, dtype=np.int32),
            behavior=np.array([0, 1 << 8], dtype=np.int32),
            hits=np.array([1, 1], dtype=np.int64),
            limit=np.array([1000, 3], dtype=np.int64),
            burst=np.zeros(2, dtype=np.int64),
            duration=np.full(2, 60_000, dtype=np.int64),
            created_at=np.full(2, now, dtype=np.int64),
            err=np.zeros(2, dtype=np.int8),
        )

    for name, eng in (
        ("local", LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")),
        ("sharded", ShardedEngine(mesh, capacity_per_shard=1 << 11,
                                  route="device", dedup="device")),
    ):
        rc = eng.check_columns(batch(), now_ms=now)
        assert not rc.err.any(), (name, rc.err)
        # both configs were really applied: the level-1 row reports the
        # small limit's config, the carrier's own level the big one
        assert int(rc.limit[1]) == 3, name
        # two sequential applications of the same key happened (the second
        # sees the first's consumption under ITS config rules)
        assert int(rc.status[0]) == 1 or int(rc.remaining[1]) < 3, name


def test_same_level_cascade_rows_aggregate(frozen_now, mesh):
    """Opposite direction: the SAME (fp, level) across two DIFFERENT
    cascades still aggregates in-trace (50 users of one tenant cost one
    kernel row, hits summed) — the PR-3 machinery composes with levels."""
    from gubernator_tpu.parallel.sharded import ShardedEngine

    now = NOW
    eng = ShardedEngine(mesh, capacity_per_shard=1 << 11, route="device",
                        dedup="device")
    ten = fingerprint("casc", "tenant:shared")
    cols = RequestColumns(
        fp=np.array([fingerprint("casc", "user:a"), ten,
                     fingerprint("casc", "user:b"), ten], dtype=np.int64),
        algo=np.zeros(4, dtype=np.int32),
        behavior=np.array([0, 1 << 8, 0, 1 << 8], dtype=np.int32),
        hits=np.array([1, 1, 1, 1], dtype=np.int64),
        limit=np.array([10, 6, 10, 6], dtype=np.int64),
        burst=np.zeros(4, dtype=np.int64),
        duration=np.full(4, 60_000, dtype=np.int64),
        created_at=np.full(4, now, dtype=np.int64),
        err=np.zeros(4, dtype=np.int8),
    )
    rc = eng.check_columns(cols, now_ms=now)
    # both tenant rows see the aggregate (6 - 2 = 4 remaining)
    assert int(rc.remaining[1]) == 4 and int(rc.remaining[3]) == 4
    assert int(rc.remaining[0]) == 4  # carrier folded min(9, tenant 4)


def test_cascade_multi_pass_and_retry_refold(frozen_now):
    """Duplicate fps force a multi-pass plan (no in-trace fold); the host
    fold must still produce the combined verdict."""
    now = NOW
    eng = LocalEngine(capacity=1 << 12, write_mode="xla", wire="full")
    u = fingerprint("casc", "mp-user")
    t = fingerprint("casc", "mp-tenant")
    # two cascades sharing the tenant at level 1 + a plain duplicate of the
    # user key → host planner splits passes
    cols = RequestColumns(
        fp=np.array([u, t, u, t], dtype=np.int64),
        algo=np.zeros(4, dtype=np.int32),
        behavior=np.array([0, 1 << 8, 0, 1 << 8], dtype=np.int32),
        hits=np.array([1, 1, 1, 1], dtype=np.int64),
        limit=np.array([10, 2, 10, 2], dtype=np.int64),
        burst=np.zeros(4, dtype=np.int64),
        duration=np.full(4, 60_000, dtype=np.int64),
        created_at=np.full(4, now, dtype=np.int64),
        err=np.zeros(4, dtype=np.int8),
    )
    rc = eng.check_columns(cols, now_ms=now)
    rc = eng.check_columns(cols, now_ms=now)
    # tenant (limit 2) exhausted after 2-3 hits → second round denies, and
    # the fold propagates OVER to both carriers
    assert int(rc.status[1]) == 1 or int(rc.status[3]) == 1
    assert int(rc.status[0]) == 1 and int(rc.status[2]) == 1


def test_cascade_pipelined_mesh_fold(frozen_now, mesh):
    """The PIPELINED mesh path (prepare/issue/finish split the daemon's
    runner drives) must fold cascade verdicts host-side: single_pass plans
    look 'single pass' but the routed per-shard programs cannot fold
    in-trace — regression for the capability gate."""
    from gubernator_tpu.ops.engine import (
        finish_check_columns,
        issue_check_columns,
        prepare_check_columns,
    )
    from gubernator_tpu.parallel.sharded import ShardedEngine

    now = NOW
    eng = ShardedEngine(mesh, capacity_per_shard=1 << 11, route="device",
                        dedup="device")
    cols = _cascade_cols(now, user_hits=4)
    for _ in range(2):  # second check drives the user level (5) over
        pending = prepare_check_columns(eng, cols, now_ms=now)
        pending = issue_check_columns(eng, pending)
        rc, _delta = finish_check_columns(
            eng, pending, lambda fn: fn()
        )
    assert int(rc.status[0]) == 1  # folded deny-if-any on the carrier
    assert int(rc.status[1]) == 0  # tenant level itself still under


# ----------------------------------------------------- forward compatibility


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper



@async_test
async def test_mixed_version_cluster_unknown_algorithm():
    """Mixed-version two-daemon cluster stub: a 'newer' client/peer sends
    an algorithm enum this build doesn't speak. The receiving daemon — and
    the OWNER it forwards to — answer that ITEM with the reference-worded
    error row; the rest of the batch succeeds, and V1Client surfaces it
    per item."""
    from tests.cluster import Cluster

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.proto import gubernator_pb2 as pb

    cluster = await Cluster.start(2)
    try:
        d0 = cluster.daemons[0]
        # find a key OWNED BY THE OTHER daemon so the request is forwarded
        # (the unknown enum crosses the peer wire, like a newer peer would)
        fwd_key = None
        for i in range(100):
            k = f"fwd{i}"
            if not d0.is_self(d0.get_peer("mv_" + k)):
                fwd_key = k
                break
        assert fwd_key is not None
        c = V1Client(d0.conf.grpc_address)
        reqs = [
            pb.RateLimitReq(name="mv", unique_key=fwd_key, hits=1, limit=5,
                            duration=60_000, algorithm=7),
            pb.RateLimitReq(name="mv", unique_key="ok", hits=1, limit=5,
                            duration=60_000),
        ]
        resp = await c.get_rate_limits(reqs)
        assert resp.responses[0].error == "invalid rate limit algorithm"
        assert resp.responses[1].error == ""
        assert resp.responses[1].remaining == 4
        await c.close()
    finally:
        await cluster.stop()


@async_test
async def test_cascade_routes_to_level0_owner_and_returns_levels():
    """Two-daemon cluster: a cascade whose LEVEL-0 key is owned by the
    remote daemon forwards whole — the owner expands/evaluates all levels
    in its one dispatch and the per-level responses ride back over the
    peer wire."""
    from tests.cluster import Cluster

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.proto import gubernator_pb2 as pb

    cluster = await Cluster.start(2)
    try:
        d0 = cluster.daemons[0]
        fwd_key = None
        for i in range(100):
            k = f"cu{i}"
            if not d0.is_self(d0.get_peer("cm_" + k)):
                fwd_key = k
                break
        assert fwd_key is not None
        c = V1Client(d0.conf.grpc_address)
        r = pb.RateLimitReq(name="cm", unique_key=fwd_key, hits=3, limit=5,
                            duration=60_000)
        r.cascade.add(name="cm_tenant", unique_key="acme", limit=4,
                      duration=60_000)
        resp = await c.get_rate_limits([r])
        top = resp.responses[0]
        assert len(top.cascade) == 1
        assert top.status == 0 and top.remaining == 1  # min(2, 1)
        resp = await c.get_rate_limits([r])
        top = resp.responses[0]
        assert top.status == 1  # tenant level (4) denies 3+3
        assert top.cascade[0].status == 1
        await c.close()
    finally:
        await cluster.stop()


@async_test
async def test_cascade_too_deep_is_per_item_error():
    from tests.cluster import Cluster

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.proto import gubernator_pb2 as pb

    cluster = await Cluster.start(1, cascade_max_levels=3)
    try:
        d = cluster.daemons[0]
        c = V1Client(d.conf.grpc_address)
        r = pb.RateLimitReq(name="deep", unique_key="k", hits=1, limit=5,
                            duration=60_000)
        for i in range(3):  # 1 + 3 levels > 3
            r.cascade.add(name=f"lvl{i}", unique_key="x", limit=5,
                          duration=60_000)
        ok = pb.RateLimitReq(name="deep", unique_key="fine", hits=1, limit=5,
                             duration=60_000)
        resp = await c.get_rate_limits([r, ok])
        assert resp.responses[0].error == (
            "Cascade levels list too large; max size is '3'"
        )
        assert resp.responses[1].error == "" and resp.responses[1].remaining == 4
        await c.close()
    finally:
        await cluster.stop()
