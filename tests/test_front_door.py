"""Serving-plane tests: fused wire→grid parse parity, multi-worker front
door ordering, adaptive batching, and the bounded ring.

Parity contract: the raw byte path (native parse → fused lane staging →
native encode) must be BYTE-IDENTICAL to the pb path (message parse →
columns → pack → dispatch → message encode) for every routing shape —
that's what makes the fused path a pure perf change. GUBER_WIRE_COMPACT=0
(full-width) remains the deeper oracle below both."""

import asyncio
import functools
import time

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.ops.batch import RequestColumns, ResponseColumns
from gubernator_tpu.ops.engine import LocalEngine, ms_now
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.service.batcher import Batcher
from gubernator_tpu.service.daemon import Daemon
from gubernator_tpu.service.wire import WireBatch, wire_batch_from_wire
from gubernator_tpu.types import Behavior

from tests.cluster import daemon_config

nat = native.load()
pytestmark = pytest.mark.skipif(nat is None, reason="native toolchain unavailable")


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(i: int, now: int, **kw) -> "pb.RateLimitReq":
    d = dict(
        name="fd", unique_key=f"k{i}", hits=1, limit=100 + i,
        duration=60_000, created_at=now,
    )
    d.update(kw)
    return pb.RateLimitReq(**d)


def mixed_corpus(now: int):
    """Every fused-path edge in one batch sequence: plain encodable rows,
    error rows, duplicates (unique-fp fallback), and each non-wire-encodable
    field (hits overflow, Gregorian, explicit leaky burst, oversized
    limit)."""
    return [
        # all-encodable, all-unique — the fused fast path
        [req(i, now) for i in range(8)],
        # error rows isolated, batch still served
        [req(0, now), pb.RateLimitReq(unique_key="nn", hits=1, limit=1),
         pb.RateLimitReq(name="nk", hits=1, limit=1), req(3, now)],
        # duplicate keys → host pass planner (sequential same-key semantics)
        [req(7, now), req(7, now), req(9, now)],
        # hits beyond the 18-bit lane budget → full-width fallback
        [req(11, now, hits=1 << 19, limit=1 << 24)],
        # Gregorian duration (behavior bit) → full-width fallback
        [req(12, now, behavior=int(Behavior.DURATION_IS_GREGORIAN),
             duration=4)],  # GregorianDays: end-of-day is call-stable
        # explicit leaky burst → full-width fallback
        [req(13, now, algorithm=1, burst=7, limit=50)],
        # limit beyond int32 → per-item validation error via the fallback
        [req(14, now, limit=1 << 40), req(15, now)],
        # DRAIN/RESET bits ride the wire; GLOBAL/NO_BATCHING are inert
        [req(16, now, behavior=int(Behavior.RESET_REMAINING)),
         req(17, now, behavior=int(Behavior.DRAIN_OVER_LIMIT), hits=0),
         req(18, now, behavior=int(Behavior.NO_BATCHING))],
    ]


async def _parity_daemons(corpus, raw_conf, pb_conf, raw_engine=None,
                          pb_engine=None, reset_tol_ms: int = 0):
    """Drive the SAME request sequence through a raw-bytes daemon and a
    pb-path daemon; every response must be byte-identical. `reset_tol_ms`
    relaxes ONLY reset_time (mesh-GLOBAL replica answers re-anchor at each
    daemon's serve clock, so two daemons differ by wall-clock ms — a
    cross-daemon nondeterminism, not a raw/pb divergence; every other field
    still compares exactly)."""
    d_raw = await Daemon.spawn(raw_conf, engine=raw_engine)
    d_pb = await Daemon.spawn(pb_conf, engine=pb_engine)
    try:
        for items in corpus:
            data = pb.GetRateLimitsReq(
                requests=items
            ).SerializeToString()
            raw_bytes = await d_raw.get_rate_limits_raw(data)
            resps = await d_pb.get_rate_limits(list(items))
            pb_bytes = pb.GetRateLimitsResp(
                responses=resps
            ).SerializeToString()
            if raw_bytes == pb_bytes:
                continue
            raw_msg = pb.GetRateLimitsResp.FromString(raw_bytes)
            diag = (
                f"raw/pb divergence for {items}:\n"
                f"raw={raw_msg}\npb={pb.GetRateLimitsResp(responses=resps)}"
            )
            assert reset_tol_ms > 0, diag
            assert len(raw_msg.responses) == len(resps), diag
            for a, b in zip(raw_msg.responses, resps):
                assert abs(a.reset_time - b.reset_time) <= reset_tol_ms, diag
                a.reset_time = b.reset_time = 0
                assert a == b, diag
        return d_raw, d_pb
    finally:
        await d_raw.close()
        await d_pb.close()


@async_test
async def test_fused_parity_local_compact():
    """Byte-for-byte parity on the compact-wire local engine — the fused
    lane path against the pb path, across encodable, error, duplicate,
    non-encodable and behavior-bit batches."""
    now = ms_now()
    conf = lambda: daemon_config(http_address="")
    d_raw, _ = await _parity_daemons(
        mixed_corpus(now),
        conf(), conf(),
        raw_engine=LocalEngine(capacity=8192, wire="compact"),
        pb_engine=LocalEngine(capacity=8192, wire="compact"),
    )
    # the plain batches actually rode the fused path; the exotic ones fell
    # back — both must have happened for this parity run to mean anything
    assert d_raw.batcher.fused_dispatches > 0
    assert d_raw.batcher.column_dispatches + d_raw.batcher.wire_fallbacks > 0


@async_test
async def test_fused_parity_full_width_oracle():
    """Same corpus with GUBER_WIRE_COMPACT semantics OFF (full-width
    engines): the raw path must still match the pb path byte-for-byte —
    the fused path simply never engages."""
    now = ms_now()
    conf = lambda: daemon_config(http_address="")
    d_raw, _ = await _parity_daemons(
        mixed_corpus(now),
        conf(), conf(),
        raw_engine=LocalEngine(capacity=8192, wire="full"),
        pb_engine=LocalEngine(capacity=8192, wire="full"),
    )
    assert d_raw.batcher.fused_dispatches == 0


@async_test
async def test_fused_parity_sharded_engine():
    """Raw/pb parity through the mesh engine (8-dev virtual CPU mesh,
    GLOBAL served by the collective replica plane standalone): the fused
    path declines mesh engines, and the fallback must stay byte-identical
    — including GLOBAL-behavior rows."""
    now = ms_now()
    corpus = [
        [req(i, now) for i in range(4)],
        [req(5, now, behavior=int(Behavior.GLOBAL)),
         req(6, now), pb.RateLimitReq(name="nk", hits=1, limit=1)],
        [req(5, now, behavior=int(Behavior.GLOBAL), hits=2)],
    ]
    await _parity_daemons(
        corpus,
        daemon_config(engine="sharded", cache_size=4096, http_address=""),
        daemon_config(engine="sharded", cache_size=4096, http_address=""),
        reset_tol_ms=5_000,
    )


@async_test
async def test_fused_parity_force_global():
    """GUBER_FORCE_GLOBAL flips every request to GLOBAL before routing; the
    raw path applies it to the columns only (GLOBAL is kernel-inert, the
    parser lanes stay valid) and must still match the pb path exactly."""
    now = ms_now()

    def conf():
        c = daemon_config(http_address="")
        c.behaviors.force_global = True
        return c

    d_raw, _ = await _parity_daemons(
        [[req(i, now) for i in range(6)], [req(2, now, hits=3)]],
        conf(), conf(),
        raw_engine=LocalEngine(capacity=8192, wire="compact"),
        pb_engine=LocalEngine(capacity=8192, wire="compact"),
    )
    assert d_raw.batcher.fused_dispatches > 0


@async_test
async def test_multi_worker_slicing_order():
    """N front-door workers + concurrent raw requests: every request's
    slice of the coalesced response must line up with ITS items (the limit
    field echoes the request, so a mis-slice is visible immediately)."""
    conf = daemon_config(http_address="")
    conf.behaviors.front_workers = 4
    conf.behaviors.batch_wait_ms = 2.0
    d = await Daemon.spawn(
        conf, engine=LocalEngine(capacity=1 << 15, wire="compact")
    )
    try:
        now = ms_now()
        R, B = 24, 64

        async def one(r: int):
            items = [
                pb.RateLimitReq(
                    name="ord", unique_key=f"r{r}b{i}", hits=1,
                    limit=1000 + r * B + i, duration=60_000, created_at=now,
                )
                for i in range(B)
            ]
            data = pb.GetRateLimitsReq(requests=items).SerializeToString()
            out = pb.GetRateLimitsResp.FromString(
                await d.get_rate_limits_raw(data)
            )
            assert len(out.responses) == B
            for i, resp in enumerate(out.responses):
                assert resp.limit == 1000 + r * B + i, (r, i)
                assert resp.remaining == 1000 + r * B + i - 1, (r, i)

        await asyncio.gather(*(one(r) for r in range(R)))
        # distinct keys, all encodable: the whole run rides the fused path
        assert d.batcher.fused_dispatches > 0
        assert d.batcher.wire_fallbacks == 0
    finally:
        await d.close()


# --------------------------------------------------------- batcher units


def _cols(rows: int, base: int = 0) -> RequestColumns:
    n = rows
    return RequestColumns(
        fp=np.arange(base + 1, base + n + 1, dtype=np.int64),
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 100, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        created_at=np.full(n, 1_700_000_000_000, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


class StubRunner:
    """Echo runner: gates the FIRST dispatch on an event (simulating a busy
    engine) and records per-dispatch row counts."""

    def __init__(self):
        self.gate: "asyncio.Event | None" = None
        self.dispatch_rows = []

    async def check_wire(self, parts, span=None):
        return None  # force the columns path

    async def check(self, cols, now_ms=None, span=None):
        self.dispatch_rows.append(cols.fp.shape[0])
        if self.gate is not None and len(self.dispatch_rows) == 1:
            await self.gate.wait()
        n = cols.fp.shape[0]
        return ResponseColumns(
            status=np.zeros(n, dtype=np.int32),
            limit=cols.limit.copy(),
            remaining=cols.limit - cols.hits,
            reset_time=np.zeros(n, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )


@async_test
async def test_adaptive_window_closes_on_rows():
    """With the engine busy, the adaptive window must close on accumulated
    rows — NOT ride out the (deliberately huge) wall-clock window."""
    runner = StubRunner()
    runner.gate = asyncio.Event()
    b = Batcher(
        runner, batch_wait_ms=2_000.0, coalesce_limit=4096,
        workers=1, adaptive=True, close_rows=128,
    )
    t0 = time.perf_counter()
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)  # worker picked it up and is gated
    rest = [asyncio.ensure_future(b.check(_cols(16, base=100 * (i + 1))))
            for i in range(8)]  # 128 pending rows ≥ close_rows
    await asyncio.sleep(0.05)
    runner.gate.set()
    await asyncio.gather(first, *rest)
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"window did not close on rows ({elapsed:.2f}s)"
    assert b.adaptive_closes >= 1
    # the 8 backlogged enqueues coalesced rather than dispatching singly
    assert max(runner.dispatch_rows) >= 128
    await b.drain()


@async_test
async def test_adaptive_idle_engine_skips_window():
    """Light load: with no dispatch in flight the window closes
    immediately — a lone request must not pay the batch window."""
    runner = StubRunner()
    b = Batcher(runner, batch_wait_ms=500.0, workers=2, adaptive=True)
    t0 = time.perf_counter()
    await b.check(_cols(4))
    assert time.perf_counter() - t0 < 0.3
    assert b.adaptive_closes >= 1 and b.window_expires == 0
    await b.drain()


@async_test
async def test_bounded_ring_backpressure():
    """Enqueues past max_queue_rows wait for drain progress instead of
    growing the queue without limit."""
    runner = StubRunner()
    runner.gate = asyncio.Event()
    b = Batcher(
        runner, batch_wait_ms=0.1, coalesce_limit=64, workers=1,
        adaptive=True, max_queue_rows=32,
    )
    first = asyncio.ensure_future(b.check(_cols(16)))
    await asyncio.sleep(0.05)  # in flight, engine gated
    second = asyncio.ensure_future(b.check(_cols(32, base=100)))
    await asyncio.sleep(0.02)
    third = asyncio.ensure_future(b.check(_cols(16, base=200)))
    await asyncio.sleep(0.1)
    assert not third.done(), "third enqueue should be backpressured"
    assert b._pending_rows == 32  # only the admitted batch pends
    runner.gate.set()
    await asyncio.gather(first, second, third)
    await b.drain()


@async_test
async def test_queue_gauge_set_once_per_flush():
    """The queue_length gauge is observed per FLUSH, not per enqueue —
    hot-path metric churn at request rates (PR-3 follow-through)."""

    class GaugeSpy:
        def __init__(self):
            self.sets = 0

        def set(self, v):
            self.sets += 1

    class MetricsSpy:
        def __init__(self):
            self.queue_length = GaugeSpy()

        def __getattr__(self, name):
            class _Noop:
                def labels(self, **kw):
                    return self

                def observe(self, v, exemplar=None):
                    pass

                def inc(self, v=1):
                    pass

            return _Noop()

    runner = StubRunner()
    spy = MetricsSpy()
    b = Batcher(runner, batch_wait_ms=50.0, workers=1, adaptive=True,
                close_rows=1 << 20, metrics=spy)
    futs = [asyncio.ensure_future(b.check(_cols(4, base=10 * i)))
            for i in range(16)]
    await asyncio.gather(*futs)
    await b.drain()
    # 16 enqueues; far fewer flushes — and the gauge only moved per flush
    assert spy.queue_length.sets <= len(runner.dispatch_rows)


@async_test
async def test_runner_check_wire_matches_columns():
    """Engine-level fused parity: runner.check_wire over native parser
    lanes == runner.check over the equivalent columns, field for field."""
    from gubernator_tpu.service.runner import EngineRunner

    now = ms_now()
    items = [req(i, now) for i in range(32)]
    data = pb.GetRateLimitsReq(requests=items).SerializeToString()
    wb, _, _, _ = wire_batch_from_wire(data)
    assert wb.encodable.all()

    r_wire = EngineRunner(LocalEngine(capacity=4096, wire="compact"))
    r_cols = EngineRunner(LocalEngine(capacity=4096, wire="compact"))
    try:
        rc1 = await r_wire.check_wire([wb], now_ms=now)
        assert rc1 is not None, "fused path should engage"
        rc2 = await r_cols.check(wb.cols, now_ms=now)
        for f in ResponseColumns._fields:
            np.testing.assert_array_equal(
                getattr(rc1, f), getattr(rc2, f), err_msg=f
            )
        # full-width engine declines
        r_full = EngineRunner(LocalEngine(capacity=4096, wire="full"))
        assert await r_full.check_wire([wb], now_ms=now) is None
        r_full.close()
    finally:
        r_wire.close()
        r_cols.close()
