"""Table resize/rehash, Store write-through hook, and MULTI_REGION
cross-datacenter replication tests."""

import asyncio
import functools

import numpy as np
import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, MINUTE

from gubernator_tpu.proto import gubernator_pb2 as pb
from tests.cluster import Cluster, daemon_config, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="t", hits=1, limit=100, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=MINUTE, **kw
    )


# ----------------------------------------------------------------- resize


def test_resize_preserves_all_live_state(frozen_now):
    eng = LocalEngine(capacity=2048)  # low load factor: no insert evictions
    keys = [f"k{i}" for i in range(300)]
    out = eng.check([req(k, hits=3) for k in keys], now_ms=frozen_now)
    assert all(r.error == "" for r in out)
    before = {k: r.remaining for k, r in zip(keys, out)}
    assert eng.table.capacity == 2048
    assert eng.live_count(frozen_now) == 300

    dropped = eng.resize(8192, now_ms=frozen_now)
    assert dropped == 0
    assert eng.table.capacity == 8192
    assert eng.live_count(frozen_now) == 300

    # every bucket keeps counting where it left off
    out = eng.check([req(k, hits=1) for k in keys], now_ms=frozen_now)
    for k, r in zip(keys, out):
        assert r.remaining == before[k] - 1, k


def test_resize_drops_overflow_and_counts_it(frozen_now):
    # shrink 300 live keys into a 4-bucket table (32 slots): per-bucket
    # overflow must drop deterministically and be counted
    eng = LocalEngine(capacity=512)
    eng.check([req(f"k{i}") for i in range(300)], now_ms=frozen_now)
    live_before = eng.live_count(frozen_now)
    dropped = eng.resize(8, now_ms=frozen_now)
    assert dropped == live_before - eng.live_count(frozen_now) > 0
    assert eng.stats.evicted_unexpired >= dropped
    assert eng.live_count(frozen_now) <= 8


def test_maybe_grow_policy(frozen_now):
    eng = LocalEngine(capacity=64)
    eng.check([req(f"g{i}") for i in range(50)], now_ms=frozen_now)
    # 50/64 > 0.6 → grows
    assert eng.maybe_grow(now_ms=frozen_now) is True
    assert eng.table.capacity == 128
    # below threshold now → no further growth
    assert eng.maybe_grow(now_ms=frozen_now) is False
    # ceiling respected
    eng2 = LocalEngine(capacity=64)
    eng2.check([req(f"h{i}") for i in range(50)], now_ms=frozen_now)
    assert eng2.maybe_grow(max_capacity=64, now_ms=frozen_now) is False


# ------------------------------------------------------------------- store


def test_store_on_change_receives_persisted_fingerprints(frozen_now):
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.store import Store

    changes = []

    class Recorder(Store):
        def on_change(self, change):
            changes.append(change)

    eng = LocalEngine(capacity=256, store=Recorder())
    eng.check(
        [
            req("a"),
            RateLimitRequest(name="t", unique_key="", hits=1, limit=5, duration=MINUTE),
            req("b"),
        ],
        now_ms=frozen_now,
    )
    assert len(changes) == 1
    assert changes[0].created_at == frozen_now
    want = sorted([fingerprint("t", "a"), fingerprint("t", "b")])
    assert sorted(changes[0].fps.tolist()) == want  # invalid row excluded


# ------------------------------------------------------------ multi-region


@async_test
async def test_multi_region_hits_replicate_across_dcs():
    """Owner-side MULTI_REGION hits drain the replica bucket in the other DC
    within one sync interval."""
    c = await Cluster.start(4, dcs=["dc-a", "dc-a", "dc-b", "dc-b"])
    try:
        owner_a = c.find_owning_daemon("mr", "key-1")
        # find_owning_daemon resolves via daemons[0] (dc-a); the dc-b owner:
        dc_b = [d for d in c.daemons if d.conf.data_center == "dc-b"]
        owner_b_addr = dc_b[0].region_owners("mr_key-1")
        # from a dc-a daemon's view the dc-b owner is in ITS region picker
        owner_b_info = [
            p for p in c.daemons[0].region_owners("mr_key-1")
        ]
        assert len(owner_b_info) == 1
        owner_b = next(
            d for d in c.daemons
            if d.conf.advertise_address == owner_b_info[0].grpc_address
        )
        assert owner_b.conf.data_center == "dc-b"

        # 3 hits at the dc-a owner with MULTI_REGION
        out = await owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=3, limit=100,
                    duration=60_000, behavior=int(Behavior.MULTI_REGION),
                )
            ]
        )
        assert out[0].error == ""
        assert out[0].remaining == 97

        # dc-b owner's local bucket converges to the same drained count
        async def converged():
            r = await owner_b.get_rate_limits(
                [
                    pb.RateLimitReq(
                        name="mr", unique_key="key-1", hits=0, limit=100,
                        duration=60_000,
                    )
                ]
            )
            return r[0].remaining == 97
        await wait_for(converged, timeout_s=10)

        # and the hits do NOT ping-pong back: dc-a owner still at 97
        r = await owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=0, limit=100,
                    duration=60_000,
                )
            ]
        )
        await asyncio.sleep(0.3)  # two extra sync intervals
        assert r[0].remaining == 97

        # hits arriving at a NON-owner (forwarded via GetPeerRateLimits)
        # must also replicate: the owner-side peer path queues them too
        non_owner_a = next(
            d for d in c.daemons
            if d.conf.data_center == "dc-a" and d is not owner_a
        )
        out = await non_owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=2, limit=100,
                    duration=60_000, behavior=int(Behavior.MULTI_REGION),
                )
            ]
        )
        assert out[0].error == "" and out[0].remaining == 95

        async def converged2():
            r = await owner_b.get_rate_limits(
                [
                    pb.RateLimitReq(
                        name="mr", unique_key="key-1", hits=0, limit=100,
                        duration=60_000,
                    )
                ]
            )
            return r[0].remaining == 95
        await wait_for(converged2, timeout_s=10)
    finally:
        await c.stop()
