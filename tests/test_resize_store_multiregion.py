"""Table resize/rehash, Store write-through hook, and MULTI_REGION
cross-datacenter replication tests."""

import asyncio
import functools

import numpy as np
import pytest

from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, MINUTE

from gubernator_tpu.proto import gubernator_pb2 as pb
from tests.cluster import Cluster, daemon_config, wait_for


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


def req(key, name="t", hits=1, limit=100, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=MINUTE, **kw
    )


# ----------------------------------------------------------------- resize


def test_resize_preserves_all_live_state(frozen_now):
    eng = LocalEngine(capacity=2048)  # low load factor: no insert evictions
    keys = [f"k{i}" for i in range(300)]
    out = eng.check([req(k, hits=3) for k in keys], now_ms=frozen_now)
    assert all(r.error == "" for r in out)
    before = {k: r.remaining for k, r in zip(keys, out)}
    assert eng.table.capacity == 2048
    assert eng.live_count(frozen_now) == 300

    dropped = eng.resize(8192, now_ms=frozen_now)
    assert dropped == 0
    assert eng.table.capacity == 8192
    assert eng.live_count(frozen_now) == 300

    # every bucket keeps counting where it left off
    out = eng.check([req(k, hits=1) for k in keys], now_ms=frozen_now)
    for k, r in zip(keys, out):
        assert r.remaining == before[k] - 1, k


def test_resize_drops_overflow_and_counts_it(frozen_now):
    # shrink 300 live keys into a 4-bucket table (32 slots): per-bucket
    # overflow must drop deterministically and be counted
    eng = LocalEngine(capacity=512)
    eng.check([req(f"k{i}") for i in range(300)], now_ms=frozen_now)
    live_before = eng.live_count(frozen_now)
    dropped = eng.resize(8, now_ms=frozen_now)
    assert dropped == live_before - eng.live_count(frozen_now) > 0
    assert eng.stats.evicted_unexpired >= dropped
    assert eng.live_count(frozen_now) <= 8


def test_maybe_grow_policy(frozen_now):
    eng = LocalEngine(capacity=64)
    eng.check([req(f"g{i}") for i in range(50)], now_ms=frozen_now)
    # 50/64 > 0.6 → grows
    assert eng.maybe_grow(now_ms=frozen_now) is True
    assert eng.table.capacity == 128
    # below threshold now → no further growth
    assert eng.maybe_grow(now_ms=frozen_now) is False
    # ceiling respected
    eng2 = LocalEngine(capacity=64)
    eng2.check([req(f"h{i}") for i in range(50)], now_ms=frozen_now)
    assert eng2.maybe_grow(max_capacity=64, now_ms=frozen_now) is False


# ------------------------------------------------------------------- store


def test_store_on_change_receives_persisted_fingerprints(frozen_now):
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.store import Store

    changes = []

    class Recorder(Store):
        def on_change(self, change):
            changes.append(change)

    eng = LocalEngine(capacity=256, store=Recorder())
    eng.check(
        [
            req("a"),
            RateLimitRequest(name="t", unique_key="", hits=1, limit=5, duration=MINUTE),
            req("b"),
        ],
        now_ms=frozen_now,
    )
    assert len(changes) == 1
    assert changes[0].created_at == frozen_now
    want = sorted([fingerprint("t", "a"), fingerprint("t", "b")])
    assert sorted(changes[0].fps.tolist()) == want  # invalid row excluded


def test_store_change_set_carries_per_key_state(frozen_now):
    """on_change delivers reconstructible stored state, last occurrence per
    key (reference OnChange carries the CacheItem, store.go:66-70)."""
    from gubernator_tpu.hashing import fingerprint
    from gubernator_tpu.store import RecordingStore

    store = RecordingStore()
    eng = LocalEngine(capacity=256, store=store)
    eng.check(
        [req("a", hits=4, limit=10), req("b", hits=2, limit=20),
         req("a", hits=1, limit=10)],
        now_ms=frozen_now,
    )
    assert len(store.changes) == 1
    c = store.changes[0]
    by_fp = {int(c.fps[i]): i for i in range(c.fps.shape[0])}
    ia = by_fp[fingerprint("t", "a")]
    ib = by_fp[fingerprint("t", "b")]
    assert c.remaining[ia] == 5  # last occurrence: 10 - 4 - 1
    assert c.remaining[ib] == 18
    assert c.limit[ia] == 10 and c.duration[ia] == MINUTE
    assert c.algo[ia] == int(Algorithm.TOKEN_BUCKET)


def test_store_rehydrates_state_lost_to_restart(frozen_now):
    """A fresh engine (no snapshot) consults the Store on its device miss and
    re-applies the request against the hydrated item (reference
    algorithms.go:45-51: cache miss → Store.Get → warm from DB)."""
    from gubernator_tpu.store import DictStore

    store = DictStore()
    eng = LocalEngine(capacity=256, store=store)
    eng.check(
        [RateLimitRequest(name="t", unique_key="a", hits=4, limit=10,
                          duration=MINUTE)],
        now_ms=frozen_now,
    )
    eng2 = LocalEngine(capacity=256, store=store)  # restart, empty table
    out = eng2.check(
        [RateLimitRequest(name="t", unique_key="a", hits=1, limit=10,
                          duration=MINUTE)],
        now_ms=frozen_now + 1_000,
    )
    assert out[0].error == ""
    assert out[0].remaining == 5  # 10 - 4 (hydrated) - 1, NOT a fresh 9
    assert store.hydrated == 1


def test_store_rehydrates_on_device_routed_mesh(frozen_now):
    """Store write-through + miss-rehydrate on a ShardedEngine with
    route="device": the check dispatch rides the a2a exchange while the
    rehydrate install takes the host-pinned path — both under one engine
    (regression guard for the route split)."""
    import jax

    from gubernator_tpu.parallel import ShardedEngine, make_mesh
    from gubernator_tpu.store import DictStore

    assert len(jax.devices()) == 8
    mesh = make_mesh(8)
    store = DictStore()
    eng = ShardedEngine(mesh, capacity_per_shard=256, store=store,
                        route="device")
    keys = [f"sr{i}" for i in range(24)]
    eng.check(
        [RateLimitRequest(name="t", unique_key=k, hits=4, limit=10,
                          duration=MINUTE) for k in keys],
        now_ms=frozen_now,
    )
    assert len(store.rows) == 24
    # restart: fresh sharded table, same store
    eng2 = ShardedEngine(mesh, capacity_per_shard=256, store=store,
                         route="device")
    out = eng2.check(
        [RateLimitRequest(name="t", unique_key=k, hits=1, limit=10,
                          duration=MINUTE) for k in keys],
        now_ms=frozen_now + 1_000,
    )
    for r in out:
        assert r.error == ""
        assert r.remaining == 5  # hydrated 6 remaining, minus this hit
    assert store.hydrated == 24


def test_store_rehydrate_preserves_custom_leaky_burst(frozen_now):
    """The ChangeSet carries the real burst: rehydrating a custom-burst leaky
    bucket must NOT trip the burst-changed upgrade path (math.py burst
    refresh) and fail open to full burst."""
    from gubernator_tpu.store import DictStore

    def lreq(hits):
        return RateLimitRequest(
            name="t", unique_key="lb", hits=hits, limit=10, burst=20,
            duration=MINUTE, algorithm=Algorithm.LEAKY_BUCKET,
        )

    store = DictStore()
    eng = LocalEngine(capacity=256, store=store)
    (r,) = eng.check([lreq(15)], now_ms=frozen_now)
    assert r.remaining == 5  # burst 20 - 15
    eng2 = LocalEngine(capacity=256, store=store)  # restart
    (r,) = eng2.check([lreq(1)], now_ms=frozen_now)
    assert r.remaining == 4  # hydrated 5 - 1, NOT burst-refreshed to 19


def test_store_rehydrate_accrues_leak_since_write(frozen_now):
    """The ChangeSet carries the item's UpdatedAt stamp: refill accrued
    between the store write and the rehydrate is honored, matching a live
    engine (the reference CacheItem round-trips UpdatedAt through Store.Get)."""
    from gubernator_tpu.store import DictStore

    def lreq(hits, created_at):
        return RateLimitRequest(
            name="t", unique_key="lk", hits=hits, limit=10, duration=MINUTE,
            algorithm=Algorithm.LEAKY_BUCKET, created_at=created_at,
        )

    store = DictStore()
    eng = LocalEngine(capacity=256, store=store)
    (r,) = eng.check([lreq(10, frozen_now)], now_ms=frozen_now)
    assert r.remaining == 0  # drained
    t2 = frozen_now + 30_000  # half a duration later: 5 tokens leaked back
    eng2 = LocalEngine(capacity=256, store=store)  # restart
    (r,) = eng2.check([lreq(0, t2)], now_ms=t2)
    assert r.remaining == 5  # NOT 0: refill since the stored stamp counts


def test_store_evict_then_rehydrate(frozen_now):
    """The reference's durable-store headline (store_test.go:127): an
    unexpired item evicted under bucket pressure re-hydrates from the Store
    on its next request instead of restarting from a fresh bucket."""
    from gubernator_tpu.ops.batch import RequestColumns
    from gubernator_tpu.ops.table2 import K
    from gubernator_tpu.store import DictStore

    store = DictStore()
    eng = LocalEngine(capacity=256, store=store)
    NB = eng.table.rows.shape[0]

    def cols(fps, hits, limit, duration):
        n = len(fps)
        return RequestColumns(
            fp=np.asarray(fps, dtype=np.int64),
            algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.full(n, hits, dtype=np.int64),
            limit=np.full(n, limit, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
            duration=np.full(n, duration, dtype=np.int64),
            created_at=np.full(n, frozen_now, dtype=np.int64),
            err=np.zeros(n, dtype=np.int8),
        )

    victim = 7 + NB  # bucket 7
    fillers = [7 + i * NB for i in range(2, K + 3)]  # K+1 more, same bucket
    rc = eng.check_columns(cols([victim], hits=4, limit=10, duration=MINUTE))
    assert rc.remaining[0] == 6
    # fillers expire LATER than the victim → the full bucket evicts the
    # soonest-expiring slot: the victim, while still live
    rc = eng.check_columns(cols(fillers, hits=1, limit=10, duration=2 * MINUTE))
    assert (rc.err == 0).all()
    assert eng.stats.evicted_unexpired >= 1
    rc = eng.check_columns(cols([victim], hits=1, limit=10, duration=MINUTE))
    assert rc.err[0] == 0
    assert rc.remaining[0] == 5  # hydrated 6, minus this hit
    assert store.hydrated >= 1


# ------------------------------------------------------------ multi-region


@async_test
async def test_multi_region_hits_replicate_across_dcs():
    """Owner-side MULTI_REGION hits drain the replica bucket in the other DC
    within one sync interval."""
    c = await Cluster.start(4, dcs=["dc-a", "dc-a", "dc-b", "dc-b"])
    try:
        owner_a = c.find_owning_daemon("mr", "key-1")
        # find_owning_daemon resolves via daemons[0] (dc-a); the dc-b owner:
        dc_b = [d for d in c.daemons if d.conf.data_center == "dc-b"]
        owner_b_addr = dc_b[0].region_owners("mr_key-1")
        # from a dc-a daemon's view the dc-b owner is in ITS region picker
        owner_b_info = [
            p for p in c.daemons[0].region_owners("mr_key-1")
        ]
        assert len(owner_b_info) == 1
        owner_b = next(
            d for d in c.daemons
            if d.conf.advertise_address == owner_b_info[0].grpc_address
        )
        assert owner_b.conf.data_center == "dc-b"

        # 3 hits at the dc-a owner with MULTI_REGION
        out = await owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=3, limit=100,
                    duration=60_000, behavior=int(Behavior.MULTI_REGION),
                )
            ]
        )
        assert out[0].error == ""
        assert out[0].remaining == 97

        # dc-b owner's local bucket converges to the same drained count
        async def converged():
            r = await owner_b.get_rate_limits(
                [
                    pb.RateLimitReq(
                        name="mr", unique_key="key-1", hits=0, limit=100,
                        duration=60_000,
                    )
                ]
            )
            return r[0].remaining == 97
        await wait_for(converged, timeout_s=10)

        # and the hits do NOT ping-pong back: dc-a owner still at 97
        r = await owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=0, limit=100,
                    duration=60_000,
                )
            ]
        )
        await asyncio.sleep(0.3)  # two extra sync intervals
        assert r[0].remaining == 97

        # hits arriving at a NON-owner (forwarded via GetPeerRateLimits)
        # must also replicate: the owner-side peer path queues them too
        non_owner_a = next(
            d for d in c.daemons
            if d.conf.data_center == "dc-a" and d is not owner_a
        )
        out = await non_owner_a.get_rate_limits(
            [
                pb.RateLimitReq(
                    name="mr", unique_key="key-1", hits=2, limit=100,
                    duration=60_000, behavior=int(Behavior.MULTI_REGION),
                )
            ]
        )
        assert out[0].error == "" and out[0].remaining == 95

        async def converged2():
            r = await owner_b.get_rate_limits(
                [
                    pb.RateLimitReq(
                        name="mr", unique_key="key-1", hits=0, limit=100,
                        duration=60_000,
                    )
                ]
            )
            return r[0].remaining == 95
        await wait_for(converged2, timeout_s=10)
    finally:
        await c.stop()
