"""retry_after surfaces (PR 11): denied clients back off to the exact
conforming instant.

For GCRA denials the kernel now reports the TAT-derived conforming instant
as reset_time (ops/math.py gcra_lanes), so retry_after = reset - now is
exact — waiting exactly that long ALWAYS conforms, and waiting any less
never does. The engine object API fills RateLimitResponse.retry_after_ms;
the pb path additionally rides metadata["retry_after_ms"] (frozen proto
schema). The compact wire carries it implicitly: its reset_delta IS
reset - base.
"""

import asyncio
import functools

import numpy as np

from gubernator_tpu.ops.batch import RequestColumns
from gubernator_tpu.ops.engine import LocalEngine
from gubernator_tpu.types import Algorithm, RateLimitRequest

NOW = 1_700_000_000_000


def gcols(fp, hits, limit, dur, now):
    n = fp.shape[0]
    return RequestColumns(
        fp=fp.astype(np.int64),
        algo=np.full(n, int(Algorithm.GCRA), dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, hits, dtype=np.int64),
        limit=np.full(n, limit, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
        duration=np.full(n, dur, dtype=np.int64),
        created_at=np.full(n, now, dtype=np.int64),
        err=np.zeros(n, dtype=np.int8),
    )


def test_gcra_denied_reset_is_exact_conforming_instant():
    """Retrying exactly at reset conforms; one ms earlier still denies."""
    e = LocalEngine(capacity=1 << 10, write_mode="xla")
    fp = np.array([12345], dtype=np.int64)
    limit, dur = 4, 8_000  # T = 2000 ms, tau = 8000 ms
    # drain the whole tolerance: 4 hits at t0 → TAT = t0 + 8000
    rc = e.check_columns(gcols(fp, 4, limit, dur, NOW), now_ms=NOW)
    assert int(rc.status[0]) == 0
    # an immediate 2-hit ask: tat1 = t0+8000+4000, conforms at tat1 - tau
    rc = e.check_columns(gcols(fp, 2, limit, dur, NOW + 1), now_ms=NOW + 1)
    assert int(rc.status[0]) == 1
    t_conform = int(rc.reset_time[0])
    assert t_conform == NOW + 8_000 + 2 * 2_000 - 8_000  # = NOW + 4000
    # 1 ms before the conforming instant: still denied, same bound
    rc = e.check_columns(
        gcols(fp, 2, limit, dur, t_conform - 1), now_ms=t_conform - 1
    )
    assert int(rc.status[0]) == 1
    # exactly at the conforming instant: admitted
    rc = e.check_columns(
        gcols(fp, 2, limit, dur, t_conform), now_ms=t_conform
    )
    assert int(rc.status[0]) == 0


def test_engine_object_api_fills_retry_after_ms():
    e = LocalEngine(capacity=1 << 10, write_mode="xla")
    req = RateLimitRequest(
        name="ra", unique_key="k", hits=4, limit=4, duration=8_000,
        algorithm=Algorithm.GCRA, created_at=NOW,
    )
    (r,) = e.check([req], now_ms=NOW)
    assert r.status == 0 and r.retry_after_ms == 0
    req2 = RateLimitRequest(
        name="ra", unique_key="k", hits=2, limit=4, duration=8_000,
        algorithm=Algorithm.GCRA, created_at=NOW + 1,
    )
    (r2,) = e.check([req2], now_ms=NOW + 1)
    assert r2.status == 1
    # exact TAT math: conforming instant - now
    assert r2.retry_after_ms == r2.reset_time - (NOW + 1)
    assert r2.retry_after_ms == 3_999


def test_pb_path_carries_retry_after_metadata():
    from gubernator_tpu.ops.batch import ResponseColumns
    from gubernator_tpu.service.wire import pb_from_response_columns

    rc = ResponseColumns(
        status=np.array([1, 0], dtype=np.int32),
        limit=np.array([4, 4], dtype=np.int64),
        remaining=np.array([0, 3], dtype=np.int64),
        reset_time=np.array([NOW + 2_500, NOW + 9_000], dtype=np.int64),
        err=np.zeros(2, dtype=np.int8),
    )
    a, b = pb_from_response_columns(rc, now_ms=NOW)
    assert a.metadata["retry_after_ms"] == "2500"
    assert "retry_after_ms" not in b.metadata  # allowed rows carry nothing
    # without a clock the pb stays schema-minimal (mixed callers)
    a2, _ = pb_from_response_columns(rc)
    assert "retry_after_ms" not in a2.metadata


def _async(fn):
    @functools.wraps(fn)
    def wrapper(*a, **k):
        asyncio.run(fn(*a, **k))

    return wrapper


@_async
async def test_front_door_surfaces_retry_after_metadata():
    """A denied GCRA check over the real gRPC front door carries the
    retry_after_ms metadata consistent with its reset_time."""
    import time

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon

    d = await Daemon.spawn(DaemonConfig(
        grpc_address="127.0.0.1:0", http_address="",
        cache_size=1 << 12,
        behaviors=BehaviorConfig(batch_wait_ms=0.5),
    ))
    client = V1Client(d.conf.grpc_address)
    try:
        def req(hits):
            return RateLimitRequest(
                name="ra2", unique_key="k", hits=hits, limit=2,
                duration=60_000, algorithm=Algorithm.GCRA,
            )

        resp = await client.get_rate_limits([req(2)])
        assert resp.responses[0].status == 0
        t0 = time.time_ns() // 1_000_000
        resp = await client.get_rate_limits([req(2)])
        (r,) = resp.responses
        assert r.status == 1
        ra = int(r.metadata["retry_after_ms"])
        # conforming instant ≈ 60s away (2 more hits against a drained
        # 2-per-60s budget); bound it loosely against wall clock
        assert 0 < ra <= r.reset_time - t0 + 1_000
        assert abs((r.reset_time - t0) - ra) < 5_000
    finally:
        await client.close()
        await d.close()
